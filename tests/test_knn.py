"""SpatialKNN (models/knn.py) vs the brute-force f64 oracle.

Reference test shape: the KNN suite checks transform output counts,
ordering and early stopping (models/knn/SpatialKNNTest.scala behaviors);
here the oracle is exact brute force, and the multi-device lane runs the
same transform sharded over the 8-device CPU mesh.
"""

import numpy as np
import pytest

from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.models import (CheckpointManager, SpatialKNN,
                               knn_host_truth)

NYC = (-74.25, 40.5, -73.7, 40.9)


@pytest.fixture(scope="module")
def grid():
    return get_index_system("H3")


#: both KNN engines must satisfy the same oracle: the round-5 device
#: brute pass (right side small -> one all-pairs top_k) and the ring
#: march (brute_right_max=0 forces it — the path large right sides
#: and mesh-sharded runs take)
ENGINES = [
    pytest.param({}, id="brute"),
    pytest.param({"brute_right_max": 0}, id="rings"),
]


def _pts(n, seed, bbox=NYC):
    rng = np.random.default_rng(seed)
    return np.stack([rng.uniform(bbox[0], bbox[2], n),
                     rng.uniform(bbox[1], bbox[3], n)], -1)


def _check_against_oracle(out, left, right, k, thr=None):
    ids, dist = knn_host_truth(left, right, k, thr)
    assert np.array_equal(out["right_id"], ids)
    both = np.isfinite(dist)
    assert np.allclose(out["distance"][both], dist[both], rtol=0,
                       atol=1e-12)
    assert not np.any(np.isfinite(out["distance"]) ^ both)


@pytest.mark.parametrize("eng", ENGINES)
def test_knn_matches_bruteforce(grid, eng):
    left = _pts(2000, 1)
    right = _pts(300, 2)
    knn = SpatialKNN(grid, k=5, index_resolution=7, max_iterations=32,
                     **eng)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 5)
    assert out["iterations"] < 32          # early stop engaged


@pytest.mark.parametrize("eng", ENGINES)
def test_knn_k_larger_than_candidates_nearby(grid, eng):
    """k larger than any cell's population forces multi-ring search."""
    left = _pts(500, 3)
    right = _pts(40, 4)
    knn = SpatialKNN(grid, k=7, index_resolution=8, max_iterations=64,
                     **eng)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 7)


@pytest.mark.parametrize("eng", ENGINES)
def test_knn_distance_threshold(grid, eng):
    left = _pts(800, 5)
    right = _pts(200, 6)
    thr = 0.02
    knn = SpatialKNN(grid, k=4, index_resolution=8, max_iterations=64,
                     distance_threshold=thr, **eng)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 4, thr)
    # some rows must be truncated by the threshold for the test to bite
    assert np.any(out["right_id"] < 0)


def test_knn_checkpoint_resume(grid, tmp_path):
    left = _pts(600, 7)
    right = _pts(150, 8)
    # full run
    ref = SpatialKNN(grid, k=3, index_resolution=8,
                     max_iterations=64).transform(left, right)
    # interrupted run: stop after 2 rings, then resume from checkpoint
    # (ring engine forced: checkpoint/resume is iteration-state
    # machinery, which the one-shot brute pass never touches)
    ck = CheckpointManager(str(tmp_path / "ck"))
    knn1 = SpatialKNN(grid, k=3, index_resolution=8, max_iterations=2,
                      checkpoint=ck, brute_right_max=0)
    knn1.transform(left, right)
    knn2 = SpatialKNN(grid, k=3, index_resolution=8, max_iterations=64,
                      checkpoint=ck, brute_right_max=0)
    out = knn2.transform(left, right)
    assert np.array_equal(out["right_id"], ref["right_id"])


def test_knn_sharded_8dev(grid):
    import jax
    from jax.sharding import Mesh
    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, axis_names=("data",))
    left = _pts(2048, 9)               # divisible by 8
    right = _pts(256, 10)
    knn = SpatialKNN(grid, k=5, index_resolution=7, max_iterations=32,
                     mesh=mesh)
    out = knn.transform(left, right)
    _check_against_oracle(out, left, right, 5)


@pytest.mark.parametrize("eng", ENGINES)
def test_knn_small_right_side(grid, eng):
    """k larger than the whole right set: pad with -1, no crash."""
    left = _pts(50, 11)
    right = _pts(2, 12)
    out = SpatialKNN(grid, k=5, index_resolution=8,
                     max_iterations=64, **eng).transform(left, right)
    _check_against_oracle(out, left, right, 5)
    assert np.all(out["right_id"][:, 2:] == -1)


@pytest.mark.parametrize("eng", ENGINES)
def test_knn_vertex_anchored_left_points(grid, eng):
    """Left points sitting ON cell vertices — the worst case for the
    ring separation floor (regression: the d*2*inradius bound was loose
    along hex-vertex directions and returned a non-nearest neighbour
    with no flag)."""
    right = _pts(120, 13)
    # anchor left points exactly at vertices of cells in the area
    cells = np.unique(grid.point_to_cell(_pts(64, 14), 8))
    verts, counts = grid.cell_boundary(cells)
    left = verts.reshape(-1, 2)[:256]
    out = SpatialKNN(grid, k=3, index_resolution=8,
                     max_iterations=64, **eng).transform(left, right)
    _check_against_oracle(out, left, right, 3)


# ------------------------- round-4 generality: faces / grids / geoms

@pytest.mark.parametrize("eng", ENGINES)
def test_knn_global_extent_multi_face(grid, eng):
    """BASELINE config 4 shape: pings x ports at GLOBAL extent — the
    right side spans many icosahedron faces; results must still be
    exact vs brute force (per-face windows + cross-face host pass)."""
    rng = np.random.default_rng(11)
    # 'ports': uniform sphere sample is the hardest case for the face
    # split (every face populated, all boundaries exercised)
    ports = np.stack([rng.uniform(-180, 180, 6000),
                      np.degrees(np.arcsin(rng.uniform(-1, 1, 6000)))],
                     -1)
    pings = np.stack([rng.uniform(-180, 180, 3000),
                      np.degrees(np.arcsin(rng.uniform(-1, 1, 3000)))],
                     -1)
    knn = SpatialKNN(grid, k=4, index_resolution=4, max_iterations=64,
                     **eng)
    out = knn.transform(pings, ports)
    _check_against_oracle(out, pings, ports, 4)
    # the device path must do real work: most rows resolve on device
    # (lon/lat bboxes of polar faces are gross overestimates, so some
    # cross-face flagging is expected — but not wholesale)
    assert out["rechecked"] < 0.7 * len(pings), out["rechecked"]


def test_knn_non_h3_grid_fallback():
    """Non-H3 grids take the exact blocked host path instead of
    raising (VERDICT round-3 missing #3)."""
    bng = get_index_system("BNG")
    left = _pts(500, 3, bbox=(-5.0, 50.5, 1.5, 54.0))
    right = _pts(80, 4, bbox=(-5.0, 50.5, 1.5, 54.0))
    out = SpatialKNN(bng, k=3, index_resolution=4,
                     max_iterations=16).transform(left, right)
    _check_against_oracle(out, left, right, 3)


def test_knn_geometry_rows(grid):
    """Geometry x geometry KNN with exact st_distance semantics
    (reference GridRingNeighbours joins on st_distance of geometries,
    not centroids)."""
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.core.geometry.measures import \
        pairwise_geometry_distance
    rng = np.random.default_rng(5)
    bl, br = GeometryBuilder(), GeometryBuilder()
    nl, nr = 40, 25
    for _ in range(nl):
        cx = rng.uniform(-74.05, -73.9)
        cy = rng.uniform(40.6, 40.85)
        w, h = rng.uniform(1e-3, 6e-3, 2)
        bl.add_polygon(np.array([[cx - w, cy - h], [cx + w, cy - h],
                                 [cx + w, cy + h], [cx - w, cy + h],
                                 [cx - w, cy - h]]))
    for _ in range(nr):
        cx = rng.uniform(-74.05, -73.9)
        cy = rng.uniform(40.6, 40.85)
        w, h = rng.uniform(1e-3, 6e-3, 2)
        br.add_polygon(np.array([[cx - w, cy - h], [cx + w, cy - h],
                                 [cx + w, cy + h], [cx - w, cy + h],
                                 [cx - w, cy - h]]))
    L, R = bl.finish(), br.finish()
    k = 3
    out = SpatialKNN(grid, k=k, index_resolution=8,
                     max_iterations=64).transform(L, R)
    # oracle: all-pairs exact geometry distance
    ii = np.repeat(np.arange(nl), nr)
    jj = np.tile(np.arange(nr), nl)
    dall = np.asarray(pairwise_geometry_distance(
        L.take(ii), R.take(jj))).reshape(nl, nr)
    want = np.argsort(dall, axis=1, kind="stable")[:, :k]
    wantd = np.take_along_axis(dall, want, axis=1)
    # ids can differ on exact ties; distances must match exactly
    assert np.allclose(out["distance"], wantd, rtol=0, atol=1e-12)
    got_ok = np.abs(np.take_along_axis(
        dall, out["right_id"], axis=1) - wantd) < 1e-12
    assert got_ok.all()


def test_knn_geometry_point_rows_use_device_path(grid):
    """All-POINT GeometryArrays route through the point fast path."""
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    left = _pts(300, 7)
    right = _pts(50, 8)
    bl, br = GeometryBuilder(), GeometryBuilder()
    for p in left:
        bl.add_point(p)
    for p in right:
        br.add_point(p)
    out = SpatialKNN(grid, k=3, index_resolution=7,
                     max_iterations=32).transform(bl.finish(),
                                                  br.finish())
    _check_against_oracle(out, left, right, 3)
