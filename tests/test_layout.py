"""Learned store-layout advisor (sql/layout.py): recommendation
bounds, the grid_res="auto" writer path, the rewrite parity proof, and
the mosaicstat surface.
"""

import os
import sys

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs.heat import heat
from mosaic_tpu.sql.layout import (LayoutAdvice, advise_layout,
                                   rewrite_store)
from mosaic_tpu.store.reader import ChipStore
from mosaic_tpu.store.writer import StoreWriter, write_store

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def conf():
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


@pytest.fixture()
def clean_heat():
    heat.reset()
    yield
    heat.reset()


def _set(key, val):
    _config.set_default_config(_config.apply_conf(
        _config.default_config(), key, val))


def test_advice_no_evidence_is_configured_default(conf, clean_heat):
    adv = advise_layout(record=False)
    cfg = _config.default_config()
    assert adv.grid_res == cfg.store_grid_res
    assert adv.reason.startswith("no evidence")


def test_advice_clamps_and_pow2(conf, clean_heat):
    _set("mosaic.layout.min.res", "128")
    _set("mosaic.layout.max.res", "512")
    # tiny dataset -> would want a coarse grid, clamped up to min
    lo = advise_layout(total_rows=10, record=False)
    assert lo.grid_res == 128
    # huge dataset -> would want a deep grid, clamped down to max
    hi = advise_layout(total_rows=1 << 40, record=False)
    assert hi.grid_res == 512
    mid = advise_layout(total_rows=1 << 22, record=False)
    assert 128 <= mid.grid_res <= 512
    assert mid.grid_res & (mid.grid_res - 1) == 0       # a power of two


def test_advice_skew_concentrates_the_grid(conf, clean_heat):
    """A skewed heat plane raises the occupancy exponent's denominator
    (d -> 1): the same row count justifies a deeper grid than the
    uniform workload gets."""
    uniform = advise_layout(total_rows=1 << 26, record=False)
    heat.touch(1, rows=1_000_000)          # one hot cell
    for c in range(2, 10):
        heat.touch(c, rows=100)
    skewed = advise_layout(total_rows=1 << 26, record=False)
    assert skewed.evidence["heat"]["skew"] > 2.0
    assert skewed.grid_res >= uniform.grid_res


def test_advice_records_flight_event(conf, clean_heat):
    from mosaic_tpu.obs.recorder import recorder
    recorder.reset()
    recorder.enable()
    try:
        adv = advise_layout(total_rows=1 << 20)
        evs = recorder.events("layout_advice")
        assert len(evs) == 1
        assert evs[0]["grid_res"] == adv.grid_res
    finally:
        recorder.disable()


def test_writer_auto_resolves_through_advisor(conf, clean_heat,
                                              tmp_path):
    w = StoreWriter(str(tmp_path / "auto"), grid_res="auto")
    assert w.grid_res == _config.default_config().store_grid_res
    with pytest.raises(ValueError):
        StoreWriter(str(tmp_path / "bad"), grid_res="bogus")


def test_rewrite_store_roundtrip_bit_parity(conf, clean_heat,
                                            tmp_path):
    """Re-bucketing onto a different grid proves byte-exact row
    multiset parity — including NaN payloads and negative zeros, which
    compare by bit pattern, not value."""
    rng = np.random.default_rng(4)
    n = 20_000
    pts = rng.normal(0.0, 10.0, size=(n, 2))
    v = rng.normal(size=n)
    v[:7] = np.nan
    v[7] = -0.0
    cols = {"v": v, "k": rng.integers(0, 99, n).astype(np.int32)}
    src = str(tmp_path / "src")
    dst = str(tmp_path / "dst")
    write_store(src, pts, cols, grid_res=32)
    man, adv = rewrite_store(src, dst, grid_res=256)
    assert man.grid_res == 256
    assert man.total_rows == n
    assert isinstance(adv, LayoutAdvice)
    # spot-check through the reader too: same row multiset (byte
    # exact), new bucketing
    from mosaic_tpu.sql.layout import _canonical_rows
    a = ChipStore(src).read_columns()
    b = ChipStore(dst).read_columns()
    assert np.array_equal(_canonical_rows(a), _canonical_rows(b))
    # the destination really is re-bucketed, not a file copy
    assert len(ChipStore(dst).partitions) != len(ChipStore(src)
                                                .partitions)


def test_rewrite_store_uses_source_advice(conf, clean_heat, tmp_path):
    rng = np.random.default_rng(5)
    pts = rng.uniform(-1.0, 1.0, size=(5_000, 2))
    src = str(tmp_path / "s2")
    write_store(src, pts, grid_res=64)
    man, adv = rewrite_store(src, str(tmp_path / "d2"))
    assert man.grid_res == adv.grid_res
    assert man.total_rows == 5_000


def test_mosaicstat_layout_subcommand(conf, clean_heat, tmp_path,
                                      capsys):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import mosaicstat
    finally:
        sys.path.pop(0)
    rng = np.random.default_rng(6)
    store = str(tmp_path / "store")
    write_store(store, rng.normal(0, 5, size=(10_000, 2)), grid_res=64)
    assert mosaicstat.main(["layout", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "mosaic.store.grid.res" in out
    assert mosaicstat.main(["layout", "--store", store, "--json"]) == 0
    import json
    rep = json.loads(capsys.readouterr().out)
    assert rep["grid_res"] >= 1 and rep["shard_rows"] >= 1
    # no store, no heat: still answers with the configured default
    assert mosaicstat.main(["layout"]) == 0
