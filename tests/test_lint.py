"""graftlint self-tests.

Every rule must demonstrably fire on a known-bad fixture and stay
silent on its known-good twin (a lint rule that can't fail is worse
than no rule: it certifies nothing).  Plus the framework contracts:
inline suppressions, skip-file, parse-error surfacing, the baseline
round-trip, and the CLI's JSON output schema.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from mosaic_tpu import lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GRAFTLINT = os.path.join(REPO_ROOT, "tools", "graftlint.py")


def run(rule_id, code=None, tools=None, tests=None, docs=None):
    repo = lint.Repo.from_sources(code=code, tools=tools,
                                  tests=tests, docs=docs)
    return lint.run_lint(repo, [rule_id])


def dedent(src):
    return textwrap.dedent(src).lstrip("\n")


# Minimal config.py / recorder.py stand-ins the contract rules parse.
CONFIG_SRC = dedent("""
    MOSAIC_PLANNER_FORCE_PREFIX = "mosaic.planner.force."
    KEY_KNOWN = "mosaic.known.key"
    _CONF_FIELDS = {
        KEY_KNOWN: int,
        "mosaic.other.key": str,
    }
""")

RECORDER_SRC = dedent("""
    EVENTS = frozenset({"boot", "tick"})
""")


# ------------------------------------------------------- jit hygiene

class TestJitRules:
    def test_raw_jit_fires(self):
        src = dedent("""
            import jax
            square = jax.jit(lambda x: x * x)
        """)
        found = run("jit-raw-jit", code={"mosaic_tpu/k.py": src})
        assert [f.rule for f in found] == ["jit-raw-jit"]
        assert found[0].line == 2

    def test_bare_jit_import_fires(self):
        src = dedent("""
            from jax import jit
            square = jit(lambda x: x * x)
        """)
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src})

    def test_jit_via_get_or_build_passes(self):
        src = dedent("""
            import jax
            from .perf.jit_cache import kernel_cache

            def _build():
                return jax.jit(lambda x: x * x)

            def kernel(key):
                return kernel_cache.get_or_build("square", key, _build)
        """)
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src}) == []

    def test_jit_in_choke_module_passes(self):
        src = "import jax\nf = jax.jit(lambda x: x)\n"
        assert run("jit-raw-jit",
                   code={"mosaic_tpu/perf/jit_cache.py": src}) == []

    def test_raw_device_put_fires(self):
        src = dedent("""
            import jax

            def stage(chunk):
                return jax.device_put(chunk)
        """)
        found = run("jit-raw-device-put",
                    code={"mosaic_tpu/k.py": src})
        assert [f.rule for f in found] == ["jit-raw-device-put"]

    def test_device_put_in_stream_put_callback_passes(self):
        src = dedent("""
            import jax
            from .perf.pipeline import stream

            def _stage(chunk):
                return jax.device_put(chunk)

            def go(chunks):
                return stream(chunks, put=_stage)
        """)
        assert run("jit-raw-device-put",
                   code={"mosaic_tpu/k.py": src}) == []

    def test_host_sync_in_jitted_fn_fires(self):
        src = dedent("""
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                return float(x) + 1.0

            g = jax.jit(lambda x: np.asarray(x))
        """)
        found = run("jit-host-sync", code={"mosaic_tpu/k.py": src})
        assert len(found) == 2
        assert {f.line for f in found} == {6, 8}

    def test_constant_fold_and_device_code_pass(self):
        src = dedent("""
            import jax

            @jax.jit
            def f(x):
                nan = float("nan")
                return x * 2 + nan

            def host_side(x):
                return float(x)
        """)
        assert run("jit-host-sync", code={"mosaic_tpu/k.py": src}) == []


# ---------------------------------------------------- lock discipline

class TestLockRules:
    def test_unguarded_attr_fires(self):
        src = dedent("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0
                    self.items = []

                def bump(self):
                    self.n += 1

                def push(self, x):
                    self.items.append(x)
        """)
        found = run("lock-unguarded-attr",
                    code={"mosaic_tpu/c.py": src})
        assert len(found) == 2
        assert {f.line for f in found} == {10, 13}

    def test_guarded_and_locked_helpers_pass(self):
        src = dedent("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self):
                    with self._lock:
                        self.n += 1

                def _reset_locked(self):
                    self.n = 0
        """)
        assert run("lock-unguarded-attr",
                   code={"mosaic_tpu/c.py": src}) == []

    def test_lockless_class_out_of_scope(self):
        src = dedent("""
            class Plain:
                def bump(self):
                    self.n = 1
        """)
        assert run("lock-unguarded-attr",
                   code={"mosaic_tpu/c.py": src}) == []

    def test_global_rebind_fires(self):
        src = dedent("""
            import threading

            _lock = threading.Lock()
            _conf = None

            def configure(v):
                global _conf
                _conf = v
        """)
        found = run("lock-global-state", code={"mosaic_tpu/g.py": src})
        assert [f.line for f in found] == [8]

    def test_global_rebind_under_lock_passes(self):
        src = dedent("""
            import threading

            _lock = threading.Lock()
            _conf = None

            def configure(v):
                global _conf
                with _lock:
                    _conf = v
        """)
        assert run("lock-global-state",
                   code={"mosaic_tpu/g.py": src}) == []


# ----------------------------------------------------- contract drift

class TestContractRules:
    def test_unregistered_conf_key_fires(self):
        src = 'KEY = "mosaic.unknown.key"\n'
        found = run("contract-conf-key",
                    code={"mosaic_tpu/config.py": CONFIG_SRC,
                          "mosaic_tpu/u.py": src})
        assert len(found) == 1
        assert "mosaic.unknown.key" in found[0].message

    def test_registered_and_force_prefix_keys_pass(self):
        src = dedent("""
            A = "mosaic.known.key"
            B = "mosaic.planner.force.fusion"
        """)
        assert run("contract-conf-key",
                   code={"mosaic_tpu/config.py": CONFIG_SRC,
                         "mosaic_tpu/u.py": src}) == []

    def test_conf_docs_both_directions(self):
        docs = {"docs/usage/conf.md":
                "Set `mosaic.known.key` or `mosaic.bogus.key`.\n"}
        found = run("contract-conf-docs",
                    code={"mosaic_tpu/config.py": CONFIG_SRC},
                    docs=docs)
        msgs = " | ".join(f.message for f in found)
        # registered-but-undocumented anchors at config.py ...
        assert "mosaic.other.key" in msgs
        assert any(f.path == "mosaic_tpu/config.py" for f in found)
        # ... and documented-but-unregistered anchors at the doc
        assert "mosaic.bogus.key" in msgs
        assert any(f.path == "docs/usage/conf.md" for f in found)

    def test_conf_docs_family_glob_passes(self):
        docs = {"docs/usage/conf.md":
                "All `mosaic.known.key`, `mosaic.other.key` and the "
                "`mosaic.known.*` family.\n"}
        assert run("contract-conf-docs",
                   code={"mosaic_tpu/config.py": CONFIG_SRC},
                   docs=docs) == []

    def test_bad_metric_name_fires(self):
        src = dedent("""
            def probe(metrics, n):
                metrics.count("BadName")
                metrics.gauge("fam/Mixed-Case", n)
        """)
        found = run("contract-metric-name",
                    code={"mosaic_tpu/m.py": src})
        assert len(found) == 2

    def test_good_metric_names_pass(self):
        src = dedent("""
            def probe(metrics, dev, n):
                metrics.count("fam/name")
                metrics.gauge(f"mem/{dev}/bytes", n)
        """)
        assert run("contract-metric-name",
                   code={"mosaic_tpu/m.py": src}) == []

    def test_undeclared_event_and_dead_entry_fire(self):
        src = dedent("""
            from .obs.recorder import recorder

            def go():
                recorder.record("mystery", x=1)
                recorder.record("boot")
        """)
        found = run("contract-recorder-event",
                    code={"mosaic_tpu/obs/recorder.py": RECORDER_SRC,
                          "mosaic_tpu/e.py": src})
        msgs = " | ".join(f.message for f in found)
        assert "'mystery'" in msgs      # emitted, not declared
        assert "'tick'" in msgs         # declared, never emitted

    def test_catalogue_matches_emissions_passes(self):
        src = dedent("""
            from .obs.recorder import recorder

            def go():
                recorder.record("boot")
                recorder.record("tick")
        """)
        assert run("contract-recorder-event",
                   code={"mosaic_tpu/obs/recorder.py": RECORDER_SRC,
                         "mosaic_tpu/e.py": src}) == []

    def test_missing_catalogue_is_one_finding(self):
        found = run("contract-recorder-event",
                    code={"mosaic_tpu/obs/recorder.py": "x = 1\n"})
        assert len(found) == 1
        assert "EVENTS" in found[0].message

    def test_uncovered_fault_site_fires(self):
        src = dedent("""
            from .resilience import faults

            def read(path):
                faults.maybe_fail("thing.read")
        """)
        found = run("contract-fault-coverage",
                    code={"mosaic_tpu/io/thing.py": src},
                    tests={"tests/test_x.py": "def test_ok(): pass\n"})
        assert len(found) == 1
        assert "thing.read" in found[0].message

    def test_fnmatch_covered_site_passes(self):
        src = dedent("""
            from .resilience import faults

            def read(path):
                faults.maybe_fail("thing.read")
        """)
        tests = {"tests/test_chaos.py":
                 'plan("seed=1;site=thing.*,fails=1,error=OSError")\n'}
        assert run("contract-fault-coverage",
                   code={"mosaic_tpu/io/thing.py": src},
                   tests=tests) == []


# --------------------------------------------- cancellation coverage

class TestCancelRule:
    def test_chunk_loop_without_checkpoint_fires(self):
        src = dedent("""
            def pump(chunks, consume):
                for c in chunks:
                    consume(c)
        """)
        found = run("cancel-checkpoint",
                    code={"mosaic_tpu/perf/pipeline.py": src})
        assert [f.line for f in found] == [2]

    def test_chunk_loop_with_checkpoint_passes(self):
        src = dedent("""
            def pump(chunks, consume, inflight):
                for c in chunks:
                    inflight.checkpoint()
                    consume(c)
        """)
        assert run("cancel-checkpoint",
                   code={"mosaic_tpu/perf/pipeline.py": src}) == []

    def test_chunk_loop_outside_stream_modules_out_of_scope(self):
        src = dedent("""
            def pump(chunks, consume):
                for c in chunks:
                    consume(c)
        """)
        assert run("cancel-checkpoint",
                   code={"mosaic_tpu/util.py": src}) == []

    def test_operator_boundary_without_checkpoint_fires(self):
        src = dedent("""
            def stage(op, rows):
                return op(rows)
        """)
        found = run("cancel-checkpoint",
                    code={"mosaic_tpu/sql/engine.py": src})
        assert len(found) == 1
        assert "stage()" in found[0].message

    def test_operator_boundary_with_checkpoint_passes(self):
        src = dedent("""
            def stage(op, rows, handle):
                handle._checkpoint()
                return op(rows)
        """)
        assert run("cancel-checkpoint",
                   code={"mosaic_tpu/sql/engine.py": src}) == []


# --------------------------------------- suppressions & parse errors

BAD_JIT = "import jax\nf = jax.jit(lambda x: x)"


class TestSuppression:
    def test_same_line_marker(self):
        src = (BAD_JIT +
               "  # graftlint: ignore[jit-raw-jit] — test fixture\n")
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src}) == []

    def test_comment_above_marker(self):
        src = dedent("""
            import jax
            # graftlint: ignore[jit-raw-jit] — test fixture
            f = jax.jit(lambda x: x)
        """)
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src}) == []

    def test_star_suppresses_any_rule(self):
        src = BAD_JIT + "  # graftlint: ignore[*] — test fixture\n"
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src}) == []

    def test_wrong_rule_id_does_not_suppress(self):
        src = (BAD_JIT +
               "  # graftlint: ignore[jit-host-sync] — wrong id\n")
        assert len(run("jit-raw-jit",
                       code={"mosaic_tpu/k.py": src})) == 1

    def test_skip_file(self):
        src = "# graftlint: skip-file\n" + BAD_JIT + "\n"
        assert run("jit-raw-jit", code={"mosaic_tpu/k.py": src}) == []

    def test_parse_error_surfaces_as_finding(self):
        repo = lint.Repo.from_sources(
            code={"mosaic_tpu/broken.py": "def f(:\n"})
        found = lint.run_lint(repo)
        assert [f.rule for f in found] == ["parse-error"]
        assert "syntax error" in found[0].message


# --------------------------------------------------------- baseline

class TestBaseline:
    def _findings(self):
        return run("jit-raw-jit", code={"mosaic_tpu/k.py": BAD_JIT})

    def test_round_trip(self, tmp_path):
        findings = self._findings()
        assert findings
        data = lint.baseline_from_findings(
            findings, reasons={findings[0].key: "legacy kernel"})
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(data))
        loaded = lint.load_baseline(str(p))
        new, grandfathered, stale = lint.apply_baseline(findings,
                                                        loaded)
        assert new == [] and stale == []
        assert grandfathered == findings

    def test_stale_entry_reported_when_debt_paid(self, tmp_path):
        findings = self._findings()
        data = lint.baseline_from_findings(findings)
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(data))
        loaded = lint.load_baseline(str(p))
        new, grandfathered, stale = lint.apply_baseline([], loaded)
        assert new == [] and grandfathered == []
        assert stale == [findings[0].key]

    def test_key_survives_line_drift(self, tmp_path):
        findings = self._findings()
        data = lint.baseline_from_findings(findings)
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(data))
        shifted = run("jit-raw-jit",
                      code={"mosaic_tpu/k.py": "# moved\n" + BAD_JIT})
        assert shifted[0].line != findings[0].line
        new, grandfathered, _ = lint.apply_baseline(
            shifted, lint.load_baseline(str(p)))
        assert new == [] and grandfathered == shifted

    def test_new_findings_not_absorbed_by_count(self, tmp_path):
        findings = self._findings()
        data = lint.baseline_from_findings(findings)
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(data))
        doubled = run("jit-raw-jit",
                      code={"mosaic_tpu/k.py":
                            BAD_JIT + "\ng = jax.jit(lambda y: y)\n"})
        assert len(doubled) == 2
        new, grandfathered, _ = lint.apply_baseline(
            doubled, lint.load_baseline(str(p)))
        assert len(new) == 1 and len(grandfathered) == 1

    def test_missing_file_is_empty(self, tmp_path):
        assert lint.load_baseline(str(tmp_path / "nope.json")) == {}

    def test_wrong_version_raises(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"version": 99, "findings": {}}')
        with pytest.raises(ValueError):
            lint.load_baseline(str(p))

    def test_todo_reason_fills_unexplained_entries(self):
        data = lint.baseline_from_findings(self._findings())
        ent = next(iter(data["findings"].values()))
        assert ent["reason"].startswith("TODO")


# -------------------------------------------------------------- CLI

def _cli(args, cwd=REPO_ROOT):
    return subprocess.run([sys.executable, GRAFTLINT, *args],
                          cwd=cwd, capture_output=True, text=True)


class TestCLI:
    def _mini_root(self, tmp_path):
        pkg = tmp_path / "mosaic_tpu"
        pkg.mkdir()
        (pkg / "k.py").write_text(BAD_JIT + "\n")
        return str(tmp_path)

    def test_findings_exit_1_and_json_schema(self, tmp_path):
        root = self._mini_root(tmp_path)
        r = _cli(["--root", root, "--json"])
        assert r.returncode == 1
        out = json.loads(r.stdout)
        assert out["version"] == 1
        assert out["counts"]["new"] == 1
        f = out["findings"][0]
        assert set(f) == {"rule", "path", "line", "message"}
        assert f["rule"] == "jit-raw-jit"
        assert f["path"] == "mosaic_tpu/k.py"

    def test_update_baseline_then_check_passes(self, tmp_path):
        root = self._mini_root(tmp_path)
        r = _cli(["--root", root, "--update-baseline"])
        assert r.returncode == 0
        assert "need a reason" in r.stdout     # TODO entries flagged
        r = _cli(["--root", root, "--check"])
        assert r.returncode == 0

    def test_check_fails_on_stale_entries(self, tmp_path):
        root = self._mini_root(tmp_path)
        assert _cli(["--root", root, "--update-baseline"]).returncode == 0
        (tmp_path / "mosaic_tpu" / "k.py").write_text("x = 1\n")
        r = _cli(["--root", root, "--check"])
        assert r.returncode == 1
        assert "stale" in r.stdout

    def test_unknown_rule_is_tool_error(self, tmp_path):
        r = _cli(["--root", self._mini_root(tmp_path),
                  "--rules", "no-such-rule"])
        assert r.returncode == 2

    def test_list_rules_names_every_registered_rule(self):
        r = _cli(["--list-rules"])
        assert r.returncode == 0
        for rule in lint.all_rules():
            assert rule.id in r.stdout

    def test_repo_is_clean_under_committed_baseline(self):
        """The gate CI runs: the tree + tools/graftlint_baseline.json
        must lint clean."""
        r = _cli(["--check"])
        assert r.returncode == 0, r.stdout + r.stderr


# ------------------------------------------ interprocedural: graph

def graph_of(code):
    return lint.Repo.from_sources(code=code).graph()


class TestRepoGraph:
    def test_method_and_cross_module_calls_resolve(self):
        util = dedent("""
            def helper():
                return 1
        """)
        eng = dedent("""
            from .util import helper

            class Engine:
                def run(self):
                    return self._step()

                def _step(self):
                    return helper()
        """)
        g = graph_of({"mosaic_tpu/util.py": util,
                      "mosaic_tpu/engine.py": eng})
        assert "mosaic_tpu/engine.py::Engine._step" in {
            e.callee for e in g.edges_from(
                "mosaic_tpu/engine.py::Engine.run")}
        assert "mosaic_tpu/util.py::helper" in {
            e.callee for e in g.edges_from(
                "mosaic_tpu/engine.py::Engine._step")}

    def test_builder_by_name_edge(self):
        src = dedent("""
            from .perf.jit_cache import kernel_cache

            def _build():
                return 1

            def kernel(key):
                return kernel_cache.get_or_build("k", key, _build)
        """)
        g = graph_of({"mosaic_tpu/k.py": src})
        assert "mosaic_tpu/k.py::_build" in {
            e.callee for e in g.edges_from("mosaic_tpu/k.py::kernel")}

    def test_singleton_instance_method_resolves(self):
        a = dedent("""
            class Thing:
                def poke(self):
                    return 1

            thing = Thing()
        """)
        b = dedent("""
            from .a import thing

            def go():
                thing.poke()
        """)
        g = graph_of({"mosaic_tpu/a.py": a, "mosaic_tpu/b.py": b})
        assert "mosaic_tpu/a.py::Thing.poke" in {
            e.callee for e in g.edges_from("mosaic_tpu/b.py::go")}

    def test_thread_edges_and_arg_offset(self):
        src = dedent("""
            import threading

            def _work():
                pass

            def _job(tok):
                pass

            def go(pool, tok):
                threading.Thread(target=_work).start()
                pool.submit(_job, tok)
        """)
        g = graph_of({"mosaic_tpu/t.py": src})
        by_callee = {e.callee: e for e in g.thread_edges()}
        assert by_callee["mosaic_tpu/t.py::_work"].arg_offset == 0
        assert by_callee["mosaic_tpu/t.py::_job"].arg_offset == 1

    def test_lock_closure_is_transitive(self):
        src = dedent("""
            import threading

            _lock = threading.Lock()

            def inner():
                with _lock:
                    pass

            def outer():
                inner()
        """)
        g = graph_of({"mosaic_tpu/x.py": src})
        clo = g.lock_closure()
        assert "mosaic_tpu/x.py::_lock" in clo["mosaic_tpu/x.py::outer"]


# ------------------------------------------------ lock-order family

class TestLockOrderRules:
    BAD_CYCLE = dedent("""
        import threading

        _lock_a = threading.Lock()
        _lock_b = threading.Lock()

        def _take_b():
            with _lock_b:
                pass

        def ab():
            with _lock_a:
                _take_b()

        def ba():
            with _lock_b:
                with _lock_a:
                    pass
    """)

    def test_ab_ba_cycle_fires_per_edge(self):
        found = run("lock-order-cycle",
                    code={"mosaic_tpu/x.py": self.BAD_CYCLE})
        assert len(found) == 2
        msgs = " | ".join(f.message for f in found)
        assert "_lock_a" in msgs and "_lock_b" in msgs
        assert "via" in msgs            # call-chain evidence on ab

    def test_consistent_order_passes(self):
        src = dedent("""
            import threading

            _lock_a = threading.Lock()
            _lock_b = threading.Lock()

            def ab():
                with _lock_a:
                    with _lock_b:
                        pass

            def also_ab():
                with _lock_a:
                    with _lock_b:
                        pass
        """)
        assert run("lock-order-cycle",
                   code={"mosaic_tpu/x.py": src}) == []

    def test_reentrant_call_through_callee_fires(self):
        src = dedent("""
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def _bump(self):
                    with self._lock:
                        self.n += 1

                def bump_twice(self):
                    with self._lock:
                        self._bump()
        """)
        found = run("lock-reentrant-call",
                    code={"mosaic_tpu/b.py": src})
        assert len(found) == 1
        assert "_bump" in found[0].message

    def test_rlock_reentry_exempt(self):
        src = dedent("""
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self.n = 0

                def _bump(self):
                    with self._lock:
                        self.n += 1

                def bump_twice(self):
                    with self._lock:
                        self._bump()
        """)
        assert run("lock-reentrant-call",
                   code={"mosaic_tpu/b.py": src}) == []

    def test_lexical_reentry_fires(self):
        src = dedent("""
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def oops(self):
                    with self._lock:
                        with self._lock:
                            pass
        """)
        found = run("lock-reentrant-call",
                    code={"mosaic_tpu/b.py": src})
        assert len(found) == 1
        assert "re-enters" in found[0].message


# ----------------------------------------------------- thread escape

class TestThreadEscapeRule:
    def test_unguarded_mutation_on_thread_fires(self):
        src = dedent("""
            import threading

            class Sampler:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

                def start(self):
                    def _work():
                        self.rows.append(1)
                    threading.Thread(target=_work).start()
        """)
        found = run("thread-escape-unguarded",
                    code={"mosaic_tpu/s.py": src})
        assert len(found) == 1
        assert "self.rows" in found[0].message
        assert "Sampler" in found[0].message

    def test_locked_mutation_on_thread_passes(self):
        src = dedent("""
            import threading

            class Sampler:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

                def start(self):
                    def _work():
                        with self._lock:
                            self.rows.append(1)
                    threading.Thread(target=_work).start()
        """)
        assert run("thread-escape-unguarded",
                   code={"mosaic_tpu/s.py": src}) == []

    def test_bound_method_target_is_other_rules_jurisdiction(self):
        # lock-unguarded-attr already covers method bodies; the thread
        # rule must not double-report them
        src = dedent("""
            import threading

            class Sampler:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.rows = []

                def _drain(self):
                    self.rows.append(1)

                def start(self):
                    threading.Thread(target=self._drain).start()
        """)
        assert run("thread-escape-unguarded",
                   code={"mosaic_tpu/s.py": src}) == []


# --------------------------------------------------- release pairing

MEMWATCH_SRC = dedent("""
    class DeviceMemoryLedger:
        def register(self, site, nbytes):
            return object()

        def release(self, token):
            return None

    memwatch = DeviceMemoryLedger()
""")


class TestReleasePathRule:
    def _run(self, client):
        return run("resource-release-path",
                   code={"mosaic_tpu/obs/memwatch.py": MEMWATCH_SRC,
                         "mosaic_tpu/stage.py": client})

    def test_raise_before_release_fires(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def stage(buf, work):
                tok = memwatch.register("stage", 8)
                work(buf)
                memwatch.release(tok)
        """)
        found = self._run(src)
        assert len(found) == 1
        assert "'tok'" in found[0].message

    def test_finally_twin_passes(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def stage(buf, work):
                tok = memwatch.register("stage", 8)
                try:
                    work(buf)
                finally:
                    memwatch.release(tok)
        """)
        assert self._run(src) == []

    def test_discarded_token_fires(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def stage():
                memwatch.register("stage", 8)
        """)
        found = self._run(src)
        assert len(found) == 1
        assert "discarded" in found[0].message

    def test_returned_token_escapes_passes(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def stage():
                return memwatch.register("stage", 8)
        """)
        assert self._run(src) == []

    def test_thread_handoff_to_finally_worker_passes(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def _worker(tok):
                try:
                    consume(tok)
                finally:
                    memwatch.release(tok)

            def go(pool):
                tok = memwatch.register("s", 8)
                pool.submit(_worker, tok)
        """)
        assert self._run(src) == []

    def test_thread_handoff_to_unprotected_worker_fires(self):
        src = dedent("""
            from .obs.memwatch import memwatch

            def _worker(tok):
                consume(tok)
                memwatch.release(tok)

            def go(pool):
                tok = memwatch.register("s", 8)
                pool.submit(_worker, tok)
        """)
        found = self._run(src)
        assert len(found) == 1
        assert "thread worker" in found[0].message

    def test_other_ledgers_named_register_ignored(self):
        src = dedent("""
            class KernelLedger:
                def register(self, k, v):
                    return object()

            ledger = KernelLedger()

            def note(k, v):
                ledger.register(k, v)
        """)
        assert self._run(src) == []


# ----------------------------------------------- CLI: changed, sarif

class TestCLIChangedAndSarif:
    def _git(self, *args, cwd):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t", *args],
                       cwd=cwd, check=True, capture_output=True)

    def test_changed_scopes_report_to_diff(self, tmp_path):
        pkg = tmp_path / "mosaic_tpu"
        pkg.mkdir()
        (pkg / "old.py").write_text(BAD_JIT + "\n")
        self._git("init", "-q", cwd=tmp_path)
        self._git("add", "-A", cwd=tmp_path)
        self._git("commit", "-qm", "seed", cwd=tmp_path)
        # clean tree: the committed debt is not the diff's problem
        r = _cli(["--root", str(tmp_path), "--changed", "--json"])
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.loads(r.stdout)["counts"]["new"] == 0
        # a new bad file is reported; the committed bad one stays out
        (pkg / "new.py").write_text(BAD_JIT + "\n")
        r = _cli(["--root", str(tmp_path), "--changed", "--json"])
        assert r.returncode == 1
        out = json.loads(r.stdout)
        assert {f["path"] for f in out["findings"]} == \
            {"mosaic_tpu/new.py"}

    def test_changed_without_git_falls_back_to_full(self, tmp_path):
        pkg = tmp_path / "mosaic_tpu"
        pkg.mkdir()
        (pkg / "k.py").write_text(BAD_JIT + "\n")
        r = _cli(["--root", str(tmp_path), "--changed", "--json"])
        assert r.returncode == 1
        assert "full repo" in r.stderr
        assert json.loads(r.stdout)["counts"]["new"] == 1

    def test_sarif_output_schema(self, tmp_path):
        pkg = tmp_path / "mosaic_tpu"
        pkg.mkdir()
        (pkg / "k.py").write_text(BAD_JIT + "\n")
        sarif = tmp_path / "out.sarif"
        r = _cli(["--root", str(tmp_path), "--sarif", str(sarif),
                  "--json"])
        assert r.returncode == 1
        doc = json.loads(sarif.read_text())
        assert doc["version"] == "2.1.0"
        drv = doc["runs"][0]["tool"]["driver"]
        assert drv["name"] == "graftlint"
        assert any(rd["id"] == "jit-raw-jit" for rd in drv["rules"])
        res = doc["runs"][0]["results"][0]
        assert res["ruleId"] == "jit-raw-jit"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "mosaic_tpu/k.py"
        assert loc["region"]["startLine"] >= 1


# ------------------------------------------- non-vacuity meta-gate

# One known-bad fixture per registered rule.  CI runs the test below
# on its own (`-k every_rule_fires`): a rule that cannot fail
# certifies nothing, and registering a rule without adding its bad
# fixture here fails the gate.
_RULE_BAD_FIXTURES = {
    "jit-raw-jit": dict(code={"mosaic_tpu/k.py": BAD_JIT + "\n"}),
    "jit-raw-device-put": dict(code={"mosaic_tpu/k.py": dedent("""
        import jax

        def stage(chunk):
            return jax.device_put(chunk)
    """)}),
    "jit-host-sync": dict(code={"mosaic_tpu/k.py": dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x) + 1.0
    """)}),
    "lock-unguarded-attr": dict(code={"mosaic_tpu/c.py": dedent("""
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                self.n += 1
    """)}),
    "lock-global-state": dict(code={"mosaic_tpu/g.py": dedent("""
        import threading

        _lock = threading.Lock()
        _conf = None

        def configure(v):
            global _conf
            _conf = v
    """)}),
    "contract-conf-key": dict(
        code={"mosaic_tpu/config.py": CONFIG_SRC,
              "mosaic_tpu/u.py": 'KEY = "mosaic.unknown.key"\n'}),
    "contract-conf-docs": dict(
        code={"mosaic_tpu/config.py": CONFIG_SRC},
        docs={"docs/usage/conf.md": "Set `mosaic.bogus.key`.\n"}),
    "contract-metric-name": dict(code={"mosaic_tpu/m.py": dedent("""
        def probe(metrics):
            metrics.count("BadName")
    """)}),
    "contract-recorder-event": dict(
        code={"mosaic_tpu/obs/recorder.py": RECORDER_SRC,
              "mosaic_tpu/e.py": dedent("""
        from .obs.recorder import recorder

        def go():
            recorder.record("mystery")
    """)}),
    "contract-fault-coverage": dict(
        code={"mosaic_tpu/io/thing.py": dedent("""
            from .resilience import faults

            def read(path):
                faults.maybe_fail("thing.read")
        """)},
        tests={"tests/test_x.py": "def test_ok(): pass\n"}),
    "cancel-checkpoint": dict(
        code={"mosaic_tpu/perf/pipeline.py": dedent("""
            def pump(chunks, consume):
                for c in chunks:
                    consume(c)
        """)}),
    "lock-order-cycle": dict(
        code={"mosaic_tpu/x.py": TestLockOrderRules.BAD_CYCLE}),
    "lock-reentrant-call": dict(code={"mosaic_tpu/b.py": dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def oops(self):
                with self._lock:
                    with self._lock:
                        pass
    """)}),
    "thread-escape-unguarded": dict(code={"mosaic_tpu/s.py": dedent("""
        import threading

        class Sampler:
            def __init__(self):
                self._lock = threading.Lock()
                self.rows = []

            def start(self):
                def _work():
                    self.rows.append(1)
                threading.Thread(target=_work).start()
    """)}),
    "resource-release-path": dict(
        code={"mosaic_tpu/obs/memwatch.py": MEMWATCH_SRC,
              "mosaic_tpu/stage.py": dedent("""
        from .obs.memwatch import memwatch

        def stage(buf, work):
            tok = memwatch.register("stage", 8)
            work(buf)
            memwatch.release(tok)
    """)}),
}


def test_every_rule_fires_on_its_bad_fixture():
    missing = [r.id for r in lint.all_rules()
               if r.id not in _RULE_BAD_FIXTURES]
    assert not missing, f"rules with no bad fixture: {missing}"
    unknown = set(_RULE_BAD_FIXTURES) - {r.id for r in
                                         lint.all_rules()}
    assert not unknown, f"fixtures for unregistered rules: {unknown}"
    for rid, kw in sorted(_RULE_BAD_FIXTURES.items()):
        assert run(rid, **kw), f"rule {rid} did not fire (vacuous)"
