"""ops.lookup: exactness of the branchless binary search."""

import jax.numpy as jnp
import numpy as np

from mosaic_tpu.ops.lookup import lookup, searchsorted


def test_lookup_all_sizes():
    # power-of-two sizes were a historical regression (one unroll short)
    rng = np.random.default_rng(0)
    for t in [1, 2, 3, 4, 7, 8, 15, 16, 17, 64, 100, 128, 1024]:
        table = np.unique(rng.integers(0, 1 << 60, t).astype(np.int64))
        keys = np.concatenate([table, table + 1, table - 1,
                               np.array([-1, 1 << 62], np.int64)])
        idx, found = lookup(jnp.asarray(table), jnp.asarray(keys))
        idx, found = np.asarray(idx), np.asarray(found)
        in_table = np.isin(keys, table)
        assert np.array_equal(found, in_table), t
        assert np.array_equal(table[idx[found]], keys[found]), t


def test_searchsorted_matches_numpy():
    rng = np.random.default_rng(1)
    table = np.sort(rng.integers(0, 1000, 77).astype(np.int64))
    keys = rng.integers(-10, 1010, 500).astype(np.int64)
    got = np.asarray(searchsorted(jnp.asarray(table), jnp.asarray(keys)))
    assert np.array_equal(got, np.searchsorted(table, keys, side="left"))


def test_empty_table():
    idx, found = lookup(jnp.zeros(0, jnp.int64), jnp.asarray([3, 4]))
    assert not np.any(np.asarray(found))
