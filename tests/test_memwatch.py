"""Device-memory plane (``obs.memwatch``).

Covers the acceptance surface of the memory PR: the live-buffer
ledger balancing to zero across the streamed, sharded, and fused
execution paths; the leak sentinel firing exactly one ``mem_leak``
event (visible in the flight recorder, the ``mem/leaks`` counter,
the OpenMetrics exposition, and the dashboard's ``/api/memory``);
pressure-driven chunk halving preserving bit parity; disjoint
per-query attribution under interleaved queries; budget admit/deny;
the bounded in-flight stream window; and conf-key validation.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics, recorder, to_openmetrics
from mosaic_tpu.obs.accounting import accounted, audit, meter
from mosaic_tpu.obs.memwatch import mem_budget, memwatch
from mosaic_tpu.resilience import faults


@pytest.fixture
def clean_mem():
    """Reset the obs singletons + the ledger around each test, and
    restore the process config (budget keys are mutated here)."""
    prev = _config.default_config()
    audit.reset()
    meter.reset()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    memwatch.reset()
    yield
    faults.disarm()
    _config.set_default_config(prev)
    audit.reset()
    meter.reset()
    metrics.disable()
    metrics.reset()
    recorder.reset()
    memwatch.reset()


def _set_conf(key, value):
    _config.set_default_config(
        _config.apply_conf(_config.default_config(), key, value))


@pytest.fixture
def session():
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    s = mos.SQLSession(ctx)
    s.create_table("pts", {"x": np.arange(100.0),
                           "y": np.arange(100.0) / 10.0})
    return s


def _streamed_join(npts=8192, chunk=2048):
    """A tiny warm streamed PIP join (the flagship shape)."""
    from mosaic_tpu import read_wkt
    from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              make_streamed_pip_join)
    grid = CustomIndexSystem(GridConf(0, 16, 0, 16, 2, 1.0, 1.0))
    arr = read_wkt(
        ["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))",
         "POLYGON ((8.5 1.5, 14.5 1.5, 14.5 6.5, 8.5 6.5, 8.5 1.5))"])
    idx = build_pip_index(arr, 1, grid, chips=tessellate(arr, 1, grid))
    pts = np.random.default_rng(3).uniform(0, 16, (npts, 2))
    sjoin = make_streamed_pip_join(idx, grid, polys=arr, chunk=chunk)
    sjoin(pts)                                # warm (compile)
    return sjoin, pts, (idx, grid, arr)


def _raw_stream(data, chunk, observe=None, site="pipeline.stream"):
    """stream() over a host vector with a trivial jitted kernel;
    returns the concatenated doubled output."""
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.perf.pipeline import chunk_rows, stream
    fn = jax.jit(lambda x: x * 2.0)
    out = stream(chunk_rows(len(data), chunk), compute=fn,
                 put=lambda sl: jax.device_put(
                     jnp.asarray(data[sl])),
                 consume=lambda i, sl, host: np.asarray(host),
                 observe=observe, site=site)
    return np.concatenate(out)


def _assert_books_balanced():
    assert memwatch.total_live() == 0
    assert memwatch.live_buffers() == 0
    snap = memwatch.snapshot()
    assert snap["totals"]["live_bytes"] == 0
    assert snap["totals"]["registered"] == snap["totals"]["released"]
    for dev in snap["devices"].values():
        assert dev["live_bytes"] == 0
        assert dev["peak_bytes"] > 0
    for d in memwatch.live_by_device():
        assert metrics.report()["gauges"][f"mem/live_bytes/{d}"] == 0.0


# ----------------------------------------------- ledger balance

def test_streamed_join_books_balance(clean_mem):
    sjoin, pts, _ = _streamed_join()
    memwatch.reset()                          # drop the warm run
    sjoin(pts)
    _assert_books_balanced()
    snap = memwatch.snapshot()
    sites = snap["site_peak_bytes"]
    assert sites.get("pip_join/streamed/staged", 0) > 0
    assert sites.get("pip_join/streamed/out", 0) > 0
    assert memwatch.leak_count() == 0


def test_sharded_join_books_balance(clean_mem):
    import jax
    from mosaic_tpu.parallel.pip_join import make_sharded_streamed_pip_join
    sjoin, pts, (idx, grid, arr) = _streamed_join()
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
    shj = make_sharded_streamed_pip_join(idx, grid, mesh, polys=arr,
                                         chunk=2048)
    z_ref, _ = sjoin(pts)
    memwatch.reset()
    z_sh, _ = shj(pts)
    assert np.array_equal(z_sh, z_ref)
    _assert_books_balanced()
    snap = memwatch.snapshot()
    assert snap["site_peak_bytes"].get("pip_join/sharded/staged", 0) > 0
    # a sharded staged buffer splits its bytes across the mesh devices
    assert len(snap["devices"]) >= 2


def test_fused_query_books_balance(clean_mem, session):
    _set_conf("mosaic.planner.force.fusion", "on")
    out = session.sql("SELECT count(*) AS n FROM pts "
                      "WHERE x < 50 AND y > 0.5")
    assert len(out) == 1
    assert metrics.counter_value("fusion/groups") >= 1
    _assert_books_balanced()
    snap = memwatch.snapshot()
    assert any(s.startswith("fusion/")
               for s in snap["site_peak_bytes"])
    assert memwatch.leak_count() == 0


# ----------------------------------------------- leak sentinel

def test_leak_drill_exactly_one_event_everywhere(clean_mem):
    from mosaic_tpu.obs import serve_dashboard
    sjoin, pts, _ = _streamed_join()
    memwatch.reset()
    faults.arm("site=memwatch.release,fails=1,error=OSError")
    with accounted("leak-drill", principal="mallory"):
        sjoin(pts)
    # exactly one mem_leak event, naming a pipeline site
    evs = recorder.events("mem_leak")
    assert len(evs) == 1
    assert evs[0]["site"].startswith("pip_join/streamed")
    assert evs[0]["bytes"] > 0 and evs[0]["buffers"] == 1
    assert metrics.counter_value("mem/leaks") == 1
    assert metrics.counter_value("mem/release_skipped") == 1
    assert memwatch.leak_count() == 1
    # ...and the sentinel force-released: gauges return to zero
    assert memwatch.total_live() == 0
    assert memwatch.live_buffers() == 0
    # visible in the OpenMetrics exposition
    om = to_openmetrics()
    assert "mosaic_mem_leaks_total 1" in om
    # ...and on the dashboard's memory endpoint + page
    with serve_dashboard(port=0) as h:
        base = f"http://127.0.0.1:{h.port}"
        with urllib.request.urlopen(base + "/api/memory",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["totals"]["leaks"] == 1
        assert len(snap["leaks"]) == 1
        assert snap["leaks"][0]["site"].startswith("pip_join/streamed")
        with urllib.request.urlopen(base + "/memory", timeout=10) as r:
            assert r.status == 200
    # a clean follow-up query adds no further leak events
    with accounted("clean", principal="mallory"):
        sjoin(pts)
    assert len(recorder.events("mem_leak")) == 1
    assert memwatch.leak_count() == 1


def test_clean_queries_never_fire_the_sentinel(clean_mem):
    sjoin, pts, _ = _streamed_join()
    memwatch.reset()
    for _ in range(3):
        with accounted("clean", principal="alice"):
            sjoin(pts)
    assert recorder.events("mem_leak") == []
    assert metrics.counter_value("mem/leaks") == 0
    assert memwatch.total_live() == 0


# ----------------------------------------------- pressure / shrink

def test_chunk_shrink_preserves_bit_parity(clean_mem):
    sjoin, pts, _ = _streamed_join(npts=4096, chunk=2048)
    z_ref, r_ref = sjoin(pts)
    # a budget below one staged chunk (2048 rows x 16 B) pins every
    # device past the pressure high-water mark while anything is live
    _set_conf("mosaic.mem.budget.bytes", "24000")
    z_lo, r_lo = sjoin(pts)
    assert np.array_equal(z_lo, z_ref)        # degrade, not die
    assert r_lo == r_ref
    assert metrics.counter_value("mem/chunk_shrink") > 0
    assert len(recorder.events("mem_chunk_shrink")) >= 1
    assert memwatch.total_live() == 0
    assert memwatch.leak_count() == 0


def test_raw_stream_shrink_parity_and_counter(clean_mem):
    data = np.arange(8192, dtype=np.float64)
    ref = _raw_stream(data, 1024)
    assert np.array_equal(ref, data * 2.0)
    _set_conf("mosaic.mem.budget.bytes", "6000")   # < one 8 KiB chunk
    _set_conf("mosaic.mem.pressure.high", "0.5")
    lo = _raw_stream(data, 1024)
    assert np.array_equal(lo, ref)
    assert metrics.counter_value("mem/chunk_shrink") > 0


# ----------------------------------------------- attribution

def test_interleaved_queries_disjoint_attribution(clean_mem):
    """Two concurrent streams: the small query's recorded peak must
    stay below even ONE of the big query's chunks — cross-charging
    would blow straight past that bound."""
    small = np.arange(512, dtype=np.float64)       # 1 KiB chunks
    big = np.arange(65536, dtype=np.float64)       # 128 KiB chunks
    barrier = threading.Barrier(2)
    errs = []

    def run(name, data, chunk):
        try:
            barrier.wait(timeout=10)
            with accounted(name, principal=name):
                _raw_stream(data, chunk)
        except Exception as e:                     # surface in main
            errs.append(e)

    ts = [threading.Thread(target=run, args=("small", small, 128)),
          threading.Thread(target=run, args=("big", big, 16384))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    recs = {r["principal"]: r for r in audit.records()}
    big_chunk_bytes = 16384 * 8
    assert recs["big"]["cost"]["mem_peak_bytes"] >= big_chunk_bytes
    assert 0 < recs["small"]["cost"]["mem_peak_bytes"] < big_chunk_bytes
    assert recs["small"]["trace"] != recs["big"]["trace"]
    assert memwatch.total_live() == 0
    assert memwatch.leak_count() == 0


# ----------------------------------------------- budget / admission

def test_budget_admit_and_deny(clean_mem):
    assert mem_budget.admit(1 << 40)               # no budget: always
    _set_conf("mosaic.mem.budget.bytes", "10000")
    tok = memwatch.register("test/hold", 6000)
    try:
        assert mem_budget.admit(3000) is True
        assert mem_budget.admit(5000) is False     # 6000 + 5000 > 10000
        assert metrics.counter_value("mem/admit_denied") == 1
        evs = recorder.events("mem_admit_denied")
        assert len(evs) == 1
        assert evs[0]["live_bytes"] == 6000
        assert evs[0]["budget_bytes"] == 10000
    finally:
        memwatch.release(tok)
    assert mem_budget.admit(9999) is True


def test_shrink_needed_tracks_pressure(clean_mem):
    _set_conf("mosaic.mem.budget.bytes", "10000")
    _set_conf("mosaic.mem.pressure.high", "0.8")
    assert mem_budget.shrink_needed() is False
    tok = memwatch.register("test/hold", 9000)     # pressure 0.9
    try:
        assert mem_budget.shrink_needed() is True
        assert memwatch.max_pressure() >= 0.8
    finally:
        memwatch.release(tok)
    assert mem_budget.shrink_needed() is False


# ----------------------------------------------- stream window bound

def test_stream_window_bounds_inflight_buffers(clean_mem):
    """Satellite regression: over a long stream the ledger's live
    buffer count stays a small constant — completed chunks leave the
    pipeline instead of accumulating with stream length."""
    state = {"max_buffers": 0}

    def observe(i, sl, seconds):
        state["max_buffers"] = max(state["max_buffers"],
                                   memwatch.live_buffers())

    data = np.arange(40 * 256, dtype=np.float64)
    out = _raw_stream(data, 256, observe=observe)
    assert np.array_equal(out, data * 2.0)
    # 40 chunks; window = 2 in-flight fetches (2 tokens each) + the
    # dispatched chunk + the prefetched next -> never near 40
    assert 0 < state["max_buffers"] <= 10
    assert memwatch.live_buffers() == 0


# ----------------------------------------------- switches / conf

def test_memwatch_disabled_tracks_nothing(clean_mem):
    _set_conf("mosaic.obs.mem.enabled", "false")
    assert memwatch.enabled is False
    assert memwatch.register("test/x", 1024) is None
    data = np.arange(1024, dtype=np.float64)
    out = _raw_stream(data, 256)
    assert np.array_equal(out, data * 2.0)
    assert memwatch.snapshot()["totals"]["registered"] == 0
    # budget checks pass through when the ledger is off
    _set_conf("mosaic.mem.budget.bytes", "1")
    assert mem_budget.admit(1 << 30) is True
    assert mem_budget.shrink_needed() is False


def test_conf_keys_validate():
    cfg = _config.MosaicConfig()
    cfg = _config.apply_conf(cfg, "mosaic.mem.budget.bytes", "1048576")
    assert cfg.mem_budget_bytes == 1048576
    cfg = _config.apply_conf(cfg, "mosaic.mem.budget.bytes", "0")
    assert cfg.mem_budget_bytes == 0              # 0 = unlimited
    for bad in ("abc", "-1", "1.5"):
        with pytest.raises(_config.ConfigError):
            _config.apply_conf(cfg, "mosaic.mem.budget.bytes", bad)
    cfg = _config.apply_conf(cfg, "mosaic.mem.pressure.high", "0.6")
    assert cfg.mem_pressure_high == 0.6
    for bad in ("0", "1.5", "-0.2", "nope"):
        with pytest.raises(_config.ConfigError):
            _config.apply_conf(cfg, "mosaic.mem.pressure.high", bad)
    cfg = _config.apply_conf(cfg, "mosaic.obs.mem.enabled", "false")
    assert cfg.obs_mem_enabled is False
