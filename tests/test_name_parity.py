"""Registered-function-name parity vs the reference's registrations.

Extracts every FunctionIdentifier registered by the reference
(functions/MosaicContext.scala) and demands a registered counterpart
here after normalizing spelling differences.  This is the VERDICT
round-3 "name-diff returns 0 missing" gate (missing #5).
"""

import re

import pytest

REF = ("/root/reference/src/main/scala/com/databricks/labs/mosaic/"
       "functions/MosaicContext.scala")

# reference names that are Spark-infra rather than API surface
SKIP = {
    "grid_wrapaschip",       # internal chip-wrapping helper expression
}

# reference name -> the name this framework registers it under (pure
# spelling normalizations; bodies are the same operation)
RENAME = {
    "st_dump": "st_dump",
}


def _reference_names():
    txt = open(REF).read()
    names = set(re.findall(r'FunctionIdentifier\("([a-z0-9_]+)"', txt))
    return {n for n in names if n not in SKIP}


def test_zero_missing_names():
    import mosaic_tpu.functions.context  # populate the registry
    import mosaic_tpu.functions.raster   # noqa: F401
    from mosaic_tpu.functions.registry import REGISTRY
    have = set(REGISTRY)
    ref = _reference_names()
    missing = sorted(n for n in ref
                     if RENAME.get(n, n) not in have)
    assert not missing, (f"{len(missing)} reference names missing: "
                         f"{missing}")


def test_convert_to_family_round_trips():
    import numpy as np
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    wkts = ["POINT (1 2)",
            "POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))",
            "LINESTRING (0 0, 2 2)"]
    hexes = mc.call("convert_to_hex", wkts)
    assert all(re.fullmatch(r"[0-9a-f]+", h) for h in hexes)
    # hex -> wkb -> wkt round trip
    back = mc.call("convert_to_wkt", hexes)
    assert back == mc.call("convert_to_wkt", wkts)
    js = mc.call("as_json", wkts)
    assert all(s.lstrip().startswith("{") for s in js)
    assert mc.call("as_hex", wkts) == hexes
    wkbs = mc.call("convert_to_wkb", js)
    assert [b.hex() for b in wkbs] == hexes
    arr = mc.call("convert_to_coords", wkts)
    assert len(arr) == 3


def test_alias_bodies_match():
    import numpy as np
    from mosaic_tpu.functions.context import MosaicContext
    from mosaic_tpu.core.geometry.wkt import read_wkt, write_wkt
    mc = MosaicContext.build("H3")
    g = read_wkt(["MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)), "
                  "((2 2, 3 2, 3 3, 2 2)))"])
    assert write_wkt(mc.call("flatten_polygons", g)) == \
        write_wkt(mc.call("st_dump", g))
    pt = read_wkt(["POLYGON ((0 0, 2 0, 2 2, 0 2, 0 0))"])
    assert write_wkt(mc.call("st_centroid2d", pt)) == \
        write_wkt(mc.call("st_centroid", pt))
    chips = mc.call("grid_tessellateaslong", pt, 5)
    assert chips.cell_id.dtype == np.int64
