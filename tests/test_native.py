"""Native C++ geometry kernels (mosaic_tpu/native) vs the numpy path.

Reference counterpart: the JNI boundary tests — the same results must
come out of the native and managed implementations.  When no g++ is
available the native path returns None and these tests skip (the
framework contract is graceful fallback, not hard dependency).
"""

import numpy as np
import pytest

from mosaic_tpu import native
from mosaic_tpu.bench.workloads import taxi_zones
from mosaic_tpu.core.tessellate import _pip, _poly_edges


@pytest.fixture(scope="module")
def lib():
    if native.get_lib() is None:
        pytest.skip("no C++ toolchain / native build failed")
    return native.get_lib()


def test_pip_first_match_parity(lib, rng):
    polys = taxi_zones(5)
    edges_list = [_poly_edges(polys, g) for g in range(len(polys))]
    gs = np.zeros(len(polys) + 1, np.int64)
    np.cumsum([len(e) for e in edges_list], out=gs[1:])
    flat = np.concatenate(edges_list).reshape(-1, 4)
    pts = np.stack([rng.uniform(-74.35, -73.6, 40_000),
                    rng.uniform(40.4, 41.0, 40_000)], -1)
    got = native.pip_first_match(pts, flat, gs)
    want = np.full(len(pts), -1, np.int32)
    for gi in range(len(polys)):
        inside = _pip(pts, edges_list[gi])
        want = np.where((want < 0) & inside, gi, want)
    assert np.array_equal(got, want)
    assert (got >= 0).any() and (got < 0).any()


def test_pip_host_truth_uses_native(lib):
    """pip_host_truth output is identical whichever path runs."""
    import os
    from mosaic_tpu.parallel.pip_join import pip_host_truth
    polys = taxi_zones(4)
    rng = np.random.default_rng(3)
    pts = np.stack([rng.uniform(-74.3, -73.65, 20_000),
                    rng.uniform(40.45, 40.95, 20_000)], -1)
    a = pip_host_truth(pts, polys)
    # force the numpy fallback and compare
    os.environ["MOSAIC_TPU_DISABLE_NATIVE"] = "1"
    native._LIB, native._TRIED = None, True
    try:
        b = pip_host_truth(pts, polys)
    finally:
        del os.environ["MOSAIC_TPU_DISABLE_NATIVE"]
        native._TRIED = False
    assert np.array_equal(a, b)


def test_recheck_zones_parity(lib, rng):
    """Native chip-parity recheck == the vectorized numpy recheck."""
    edges = []
    zslot = []
    gstart = [0]
    gzones = []
    for g in range(50):
        cx, cy = rng.uniform(0, 10, 2)
        n_chip = rng.integers(1, 4)
        zs = []
        for c in range(n_chip):
            r = rng.uniform(0.2, 0.6)
            ang = np.linspace(0, 2 * np.pi, 7)[:-1] + rng.uniform(0, 1)
            ring = np.stack([cx + r * np.cos(ang),
                             cy + r * np.sin(ang)], -1)
            a = ring
            b = np.roll(ring, -1, axis=0)
            for i in range(len(ring)):
                edges.append([a[i, 0], a[i, 1], b[i, 0], b[i, 1]])
                zslot.append(c)
            zs.append(100 + g * 4 + c)
        gstart.append(len(edges))
        gzones.append(zs + [-1] * (4 - len(zs)))
    edges = np.asarray(edges)
    zslot = np.asarray(zslot, np.int32)
    gstart = np.asarray(gstart, np.int64)
    gzones = np.asarray(gzones, np.int32)
    pts = rng.uniform(-1, 11, (30_000, 2))
    group = rng.integers(-1, 50, 30_000)

    got = native.recheck_zones(pts, group, edges, zslot, gstart, gzones)
    want = np.full(len(pts), -1, np.int32)
    for i in range(len(pts)):
        g = group[i]
        if g < 0:
            continue
        counts = np.zeros(4, np.int64)
        for e in range(gstart[g], gstart[g + 1]):
            ax, ay, bx, by = edges[e]
            if (ay <= pts[i, 1]) != (by <= pts[i, 1]):
                t = (pts[i, 1] - ay) / (by - ay)
                if pts[i, 0] < ax + t * (bx - ax):
                    counts[zslot[e]] += 1
        odd = np.nonzero(counts & 1)[0]
        if len(odd):
            want[i] = gzones[g, odd[0]]
    assert np.array_equal(got, want)
