"""Distributed polygon x polygon overlay (parallel/overlay.py, P3).

BASELINE config 3 shape: many small building footprints x a few large
flood zones.  The sharded 8-device path (cell-hash all_to_all exchange +
local sorted join) must equal both the single-device path and the exact
f64 host oracle.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.array import GeometryBuilder
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.parallel.overlay import (overlay_host_truth,
                                         overlay_intersects)

BBOX = (-74.05, 40.65, -73.90, 40.80)


def footprints(n, seed):
    """Small axis-aligned 'building' boxes scattered over the bbox."""
    rng = np.random.default_rng(seed)
    b = GeometryBuilder()
    for _ in range(n):
        cx = rng.uniform(BBOX[0], BBOX[2])
        cy = rng.uniform(BBOX[1], BBOX[3])
        w = rng.uniform(2e-4, 2e-3)
        h = rng.uniform(2e-4, 2e-3)
        ring = np.array([[cx - w, cy - h], [cx + w, cy - h],
                         [cx + w, cy + h], [cx - w, cy + h],
                         [cx - w, cy - h]])
        b.add_polygon(ring)
    return b.finish()


def flood_zones(seed):
    """A few large irregular zones covering parts of the bbox."""
    from mosaic_tpu.bench.workloads import nyc_zones
    return nyc_zones(n_side=3, seed=seed, bbox=BBOX)


@pytest.fixture(scope="module")
def data():
    return footprints(150, 1), flood_zones(2)


@pytest.fixture(scope="module")
def grid():
    return get_index_system("H3")


def test_overlay_single_device_matches_oracle(data, grid):
    a, b = data
    got = overlay_intersects(a, b, 9, grid)
    want = overlay_host_truth(a, b)
    assert np.array_equal(got, want)
    # the workload must exercise both outcomes
    assert want.any() and not want.all()


def test_overlay_sharded_equals_single(data, grid):
    import jax
    from jax.sharding import Mesh
    a, b = data
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("data",))
    got = overlay_intersects(a, b, 9, grid, mesh=mesh)
    want = overlay_host_truth(a, b)
    assert np.array_equal(got, want)


def test_overlay_disjoint_sets(grid):
    """Far-apart sets share no cells: all False, no pairs tested."""
    a = footprints(20, 3)
    bld = GeometryBuilder()
    ring = np.array([[-73.5, 41.2], [-73.4, 41.2], [-73.4, 41.3],
                     [-73.5, 41.3], [-73.5, 41.2]])
    bld.add_polygon(ring)
    b = bld.finish()
    got = overlay_intersects(a, b, 9, grid)
    assert not got.any()


def test_overlay_near_touch_corner(grid):
    """A footprint corner within ~1e-8 deg of a zone edge (outside):
    the f32 crossing test can miscall this, so the hazard band must
    flag it and the f64 recheck must return False (regression: a
    length-proportional hazard normalization let this ship unflagged)."""
    zone_ring = np.array([[-74.0, 40.7], [-73.95, 40.7],
                          [-73.99538953140, 40.77723034],
                          [-74.0, 40.75], [-74.0, 40.7]])
    b = GeometryBuilder()
    b.add_polygon(zone_ring)
    zones = b.finish()
    # point on the edge between verts 1 and 2, nudged outward 1e-8
    p1 = zone_ring[1]
    p2 = zone_ring[2]
    t = 0.63
    px = p1[0] + t * (p2[0] - p1[0]) + 1e-8
    py = p1[1] + t * (p2[1] - p1[1])
    w = 5e-4
    fb = GeometryBuilder()
    fb.add_polygon(np.array([[px, py - w], [px + w, py - w],
                             [px + w, py + w], [px, py + w],
                             [px, py - w]]))
    foot = fb.finish()
    got = overlay_intersects(foot, zones, 9, grid)
    want = overlay_host_truth(foot, zones)
    assert np.array_equal(got, want)


# ----------------------------- ragged pair emission + distributed area

def _host_pair_area(a, b, i, j):
    from mosaic_tpu.core.geometry.clip import (_normalize_rings,
                                               geometry_rings,
                                               ring_signed_area,
                                               rings_boolean)
    rings = rings_boolean(_normalize_rings(geometry_rings(a, i)),
                          _normalize_rings(geometry_rings(b, j)),
                          "intersection")
    return sum(ring_signed_area(r) for r in _normalize_rings(rings))


def test_intersection_area_single_device(data, grid):
    from mosaic_tpu.parallel.overlay import overlay_intersection_area
    a, b = data
    ga, gb, area = overlay_intersection_area(a, b, 9, grid)
    want = overlay_host_truth(a, b)
    got_pairs = set(zip(ga.tolist(), gb.tolist()))
    want_pairs = set(zip(*np.nonzero(want)))
    # pairs with positive intersection area == intersecting pairs
    # (boundary-touch-only pairs may drop: area 0)
    missing = want_pairs - got_pairs
    for i, j in missing:
        assert _host_pair_area(a, b, int(i), int(j)) < 1e-15
    assert not (got_pairs - want_pairs)
    # exact areas on a sampled subset
    rng = np.random.default_rng(5)
    sel = rng.choice(len(ga), size=min(25, len(ga)), replace=False)
    for k in sel:
        want_a = _host_pair_area(a, b, int(ga[k]), int(gb[k]))
        assert abs(area[k] - want_a) < 1e-12 + 1e-9 * want_a


def test_intersection_area_sharded_equals_single(data, grid):
    import jax
    from jax.sharding import Mesh
    from mosaic_tpu.parallel.overlay import overlay_intersection_area
    a, b = data
    mesh = Mesh(np.array(jax.devices()[:8]), axis_names=("data",))
    g1 = overlay_intersection_area(a, b, 9, grid)
    g2 = overlay_intersection_area(a, b, 9, grid, mesh=mesh)
    assert np.array_equal(g1[0], g2[0])
    assert np.array_equal(g1[1], g2[1])
    np.testing.assert_allclose(g1[2], g2[2], rtol=1e-12, atol=1e-15)
