"""Pallas projection kernel (ops/pallas_projection.py), interpret mode.

CPU interpret mode cannot validate the df precision (XLA:CPU contracts
the barrier-free Dekker chains — see the module docstring); these tests
pin the kernel's STRUCTURE: same lattice cells as the reference df path
everywhere except a sliver of low-margin points, and margins/facegaps in
agreement.  tests_tpu/ holds the hardware precision contract.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mosaic_tpu.core.index.h3.jaxkernel import project_lattice_jax
from mosaic_tpu.ops.pallas_projection import project_lattice_pallas


@pytest.mark.parametrize("res", [7, 9])
def test_pallas_matches_df_path_structurally(res):
    rng = np.random.default_rng(6)
    origin = (-74.0, 40.7)
    n = 20_000
    loc = np.stack([rng.uniform(-0.4, 0.4, n),
                    rng.uniform(-0.3, 0.3, n)], -1).astype(np.float32)
    f1, a1, b1, m1, g1 = [np.asarray(v) for v in project_lattice_pallas(
        jnp.asarray(loc), res, origin, interpret=True)]
    f2, a2, b2, m2, g2 = [np.asarray(v) for v in jax.jit(
        lambda p: project_lattice_jax(p, res, np.asarray(origin),
                                      precision="df"))(jnp.asarray(loc))]
    same = (f1 == f2) & (a1 == a2) & (b1 == b2)
    # disagreements can only sit on cell boundaries (tiny margins)
    assert same.mean() > 0.999
    if (~same).any():
        assert np.max(np.minimum(m1[~same], m2[~same])) < 1e-3
    np.testing.assert_allclose(m1[same], m2[same], atol=2e-3)
    np.testing.assert_allclose(g1, g2, atol=1e-5)


def test_pallas_padding_and_small_batches():
    origin = (-74.0, 40.7)
    loc = np.array([[0.01, 0.02], [-0.3, 0.25], [0.0, 0.0]], np.float32)
    f, a, b, m, g = project_lattice_pallas(jnp.asarray(loc), 9, origin,
                                           interpret=True)
    assert f.shape == (3,)
    f2, a2, b2, m2, g2 = project_lattice_jax(
        jnp.asarray(loc), 9, np.asarray(origin), precision="df")
    assert np.array_equal(np.asarray(f), np.asarray(f2))
    assert np.array_equal(np.asarray(a), np.asarray(a2))
