"""Tier-1 tests for mosaic_tpu.perf: shape bucketing, the process
kernel cache, and the double-buffered streaming executor.

The load-bearing assertions:

* bucket-boundary parity — the padded/jitted classify path must agree
  bit-for-bit with the interpreted numpy fallback at sizes 1 below, at,
  and 1 above a pow2 bucket edge (padding bugs live exactly there);
* recompile-storm guard — running the identical tessellate+join
  workload twice must add ZERO kernel-cache misses and ZERO XLA
  backend compiles the second time (one compile per (bucket, kernel),
  ever, is the whole point of the policy);
* pipeline ordering — chunk results come back in input order even
  though fetch/consume runs on a worker thread, and an injected fault
  in the worker propagates to the caller instead of hanging the pool.
"""

import numpy as np
import pytest

from mosaic_tpu import read_wkt
from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
from mosaic_tpu.core import tessellate as tess
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.perf.bucketing import (iter_size_buckets, pad_rows,
                                       pad_to_block, pow2_bucket)
from mosaic_tpu.perf.jit_cache import JitCache, kernel_cache
from mosaic_tpu.perf.pipeline import chunk_rows, donate_jit, stream
from mosaic_tpu.resilience.faults import InjectedFault


@pytest.fixture(scope="module")
def grid():
    return CustomIndexSystem(GridConf(0, 16, 0, 16, 2, 1.0, 1.0))


# --------------------------------------------------------- bucketing

def test_pow2_bucket_policy():
    assert pow2_bucket(1) == 4          # floor stops 1/2-wide compiles
    assert pow2_bucket(4) == 4
    assert pow2_bucket(5) == 8
    assert pow2_bucket(1000) == 1024
    assert pow2_bucket(1024) == 1024
    assert pow2_bucket(1025) == 2048
    assert pow2_bucket(3, floor=16) == 16
    assert pow2_bucket(100_000, cap=8192) == 8192


def test_iter_size_buckets_partition():
    sizes = np.array([3, 5, 9, 4, 17, 8, 1])
    seen = []
    for width, idx in iter_size_buckets(sizes, floor=4):
        assert np.all(sizes[idx] <= width)
        # width is the pow2 bucket of the group's smallest member and
        # every member would land in a bucket <= width
        assert width == pow2_bucket(sizes[idx].min(), floor=4)
        seen.extend(idx.tolist())
    # exact partition: every item exactly once
    assert sorted(seen) == list(range(len(sizes)))
    # deterministic: same input -> same grouping
    a = [(w, i.tolist()) for w, i in iter_size_buckets(sizes, floor=4)]
    b = [(w, i.tolist()) for w, i in iter_size_buckets(sizes, floor=4)]
    assert a == b


def test_pad_rows_and_pad_to_block():
    a = np.arange(6, dtype=np.float64).reshape(3, 2)
    p = pad_rows(a, 5, np.inf)
    assert p.shape == (5, 2)
    assert np.array_equal(p[:3], a)
    assert np.all(np.isinf(p[3:]))
    assert pad_rows(a, 3) is a          # no copy when already sized
    with pytest.raises(ValueError):
        pad_rows(a, 2)
    m = np.ones(3, dtype=bool)
    pa, pm, n = pad_to_block(8, a, m, fills=[0.0, False])
    assert n == 3 and pa.shape == (8, 2) and pm.shape == (8,)
    assert not pm[3:].any()


@pytest.mark.parametrize("P", [255, 256, 257])
def test_pair_check_parity_at_bucket_boundary(P, monkeypatch):
    """Jitted pair-check == numpy fallback at the pow2 bucket edge
    (floor=256): the padded rows must never leak into the result."""
    rng = np.random.default_rng(P)
    K = 6
    a1 = rng.uniform(0, 10, (P, K, 2))
    b1 = np.roll(a1, -1, axis=1)
    a2 = rng.uniform(0, 10, (P, 2))
    b2 = rng.uniform(0, 10, (P, 2))
    vmask = rng.random((P, K)) > 0.3
    vmask[:, 0] = True                  # no all-invalid rows
    hit_j, in_j = tess._pair_check(a1, b1, a2, b2, vmask)
    monkeypatch.setattr(tess, "_f64_jit_enabled",
                        lambda *a, **k: False)
    hit_n, in_n = tess._pair_check(a1, b1, a2, b2, vmask)
    assert np.array_equal(hit_j, hit_n)
    assert np.array_equal(in_j, in_n)


def test_tessellate_parity_jit_vs_numpy(grid, monkeypatch):
    """End-to-end: the bucketed/jitted tessellation equals the
    interpreted numpy path chip-for-chip on concave + holed input.

    (Coordinates avoid polygon edges grazing cell corners exactly —
    at such zero-area degeneracies the two float paths may round a
    sliver chip in or out differently, which is not a padding bug.)"""
    wkt = ["POLYGON ((1.31 1.73, 6.83 2.12, 5.91 6.34, 2.23 5.81,"
           " 1.31 1.73))",
           "POLYGON ((0.5 8.5, 7.5 8.5, 7.5 15.5, 0.5 15.5, 0.5 8.5),"
           " (2.5 10.5, 5.5 10.5, 5.5 13.5, 2.5 13.5, 2.5 10.5))"]
    arr = read_wkt(wkt)
    chips_jit = tessellate(arr, 1, grid)
    monkeypatch.setattr(tess, "_f64_jit_enabled",
                        lambda *a, **k: False)
    chips_np = tessellate(arr, 1, grid)
    assert np.array_equal(chips_jit.cell_id, chips_np.cell_id)
    assert np.array_equal(chips_jit.geom_id, chips_np.geom_id)
    assert np.array_equal(chips_jit.is_core, chips_np.is_core)


# ------------------------------------------------------ kernel cache

def test_jit_cache_hit_miss_eviction():
    cache = JitCache(capacity=2)
    built = []

    def builder(tag):
        def build():
            built.append(tag)
            return lambda: tag
        return build

    assert cache.get_or_build("k", 1, builder("a"))() == "a"
    assert cache.get_or_build("k", 1, builder("a2"))() == "a"  # hit
    assert cache.stats() == {"hits": 1, "misses": 1, "evictions": 0,
                             "size": 1}
    cache.get_or_build("k", 2, builder("b"))
    cache.get_or_build("k", 3, builder("c"))      # evicts key 1 (LRU)
    assert cache.stats()["evictions"] == 1
    assert len(cache) == 2
    # key 1 was evicted: rebuilding it is a miss again
    assert cache.get_or_build("k", 1, builder("a3"))() == "a3"
    assert built == ["a", "b", "c", "a3"]
    # same key, different kernel name = different entry
    cache2 = JitCache()
    cache2.get_or_build("x", 1, builder("x1"))
    assert cache2.get_or_build("y", 1, builder("y1"))() == "y1"


def test_no_recompile_on_second_identical_run(grid):
    """Recompile-storm assertion: the flagship-shaped workload
    (tessellate + jitted PIP join) compiles once per (bucket, kernel)
    — an identical second pass adds zero kernel-cache misses and zero
    XLA backend compiles."""
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.obs import install_jax_listeners, metrics, tracer
    from mosaic_tpu.parallel.pip_join import (build_pip_index, localize,
                                              make_pip_join_fn)
    install_jax_listeners()
    was_enabled = tracer.enabled
    tracer.enable()
    kernel_cache.clear()
    s0 = kernel_cache.stats()           # counters are cumulative:
    m0 = metrics.counter_value("perf/jit_cache/miss")   # use deltas
    try:
        arr = read_wkt(
            ["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))",
             "POLYGON ((8.5 8.5, 14.5 9.1, 13.9 14.3, 9.2 13.8,"
             " 8.5 8.5))"])
        rng = np.random.default_rng(5)
        pts = rng.uniform(0, 16, (20_000, 2))

        chips = tessellate(arr, 1, grid)
        idx = build_pip_index(arr, 1, grid, chips=chips)
        join = jax.jit(make_pip_join_fn(idx, grid))
        ploc = jnp.asarray(localize(idx, pts))
        jax.block_until_ready(join(ploc))

        s1 = kernel_cache.stats()
        r1 = metrics.counter_value("jax/recompiles")
        m1 = metrics.counter_value("perf/jit_cache/miss")
        # one compile per (bucket, kernel): every miss minted exactly
        # one distinct cache entry, and the miss counter agrees
        assert s1["misses"] - s0["misses"] == s1["size"]
        assert m1 - m0 == s1["misses"] - s0["misses"]

        tessellate(arr, 1, grid)                 # identical second pass
        jax.block_until_ready(join(ploc))
        s2 = kernel_cache.stats()
        r2 = metrics.counter_value("jax/recompiles")
        assert s2["misses"] == s1["misses"], "kernel cache missed again"
        assert s2["hits"] > s1["hits"]
        assert r2 == r1, "XLA recompiled on an identical second run"
    finally:
        if not was_enabled:
            tracer.disable()


def test_migrated_kernels_warm_zero_compiles():
    """The three pre-kernel_cache holdouts (overlay kernels, H3
    candidate-sampling kernel, monolithic PIP) now build through
    get_or_build: an identical second build must be a cache hit with
    zero new misses, so warm runs stay at zero compiles."""
    from mosaic_tpu.core.index.h3.system import H3IndexSystem
    from mosaic_tpu.parallel.overlay import (make_overlay_fn,
                                             make_overlay_pairs_fn)
    kernel_cache.clear()
    s0 = kernel_cache.stats()           # counters are cumulative:
    make_overlay_fn(4, 4, 8, 8)         # use deltas
    make_overlay_pairs_fn(1024, 8, 8, pair_cap=16)
    s1 = kernel_cache.stats()
    assert s1["misses"] - s0["misses"] == 2
    make_overlay_fn(4, 4, 8, 8)              # identical rebuilds: hits
    make_overlay_pairs_fn(1024, 8, 8, pair_cap=16)
    s2 = kernel_cache.stats()
    assert s2["misses"] == s1["misses"], "overlay kernel rebuilt warm"
    assert s2["hits"] - s1["hits"] == 2
    # the H3 sampling kernel shares one entry per res across index
    # instances (pre-migration it lived in a per-instance dict, so a
    # fresh H3IndexSystem recompiled and the cache counters were blind)
    xy = np.random.default_rng(0).uniform(-40, 40, (40_000, 2))
    H3IndexSystem()._point_to_cell_sample(xy, 5)
    m1 = kernel_cache.stats()["misses"]
    H3IndexSystem()._point_to_cell_sample(xy, 5)   # fresh instance
    assert kernel_cache.stats()["misses"] == m1, \
        "H3 sample kernel recompiled per instance"


# ---------------------------------------------------------- pipeline

def test_chunk_rows():
    assert chunk_rows(10, 4) == [slice(0, 4), slice(4, 8), slice(8, 10)]
    assert chunk_rows(4, 4) == [slice(0, 4)]
    assert chunk_rows(0, 4) == []
    assert chunk_rows(3, 0) == [slice(0, 1), slice(1, 2), slice(2, 3)]


def test_stream_ordering_and_consume():
    import jax
    import jax.numpy as jnp
    n, chunk = 1000, 128
    x = np.arange(n, dtype=np.float64)
    fn = jax.jit(lambda v: v * 2.0)
    out = np.empty(n)

    def put(sl):
        return jax.device_put(jnp.asarray(x[sl]))

    def consume(i, sl, host):
        out[sl] = host
        return i

    order = stream(chunk_rows(n, chunk), compute=fn, put=put,
                   consume=consume)
    assert order == list(range(len(chunk_rows(n, chunk))))
    assert np.array_equal(out, x * 2.0)
    # without put/consume: raw host outputs, in order
    outs = stream([jnp.asarray(x[sl]) for sl in chunk_rows(n, chunk)],
                  compute=fn)
    assert np.array_equal(np.concatenate(outs), x * 2.0)
    assert stream([], compute=fn) == []


def test_stream_accepts_generator_source():
    """Regression: ``stream`` must accept a LAZY chunk iterator (the
    chip store's scan path) — same results as a list source, pulled at
    most one chunk ahead of the running compute (the double-buffer
    window), and never materialized into a list."""
    import jax
    import jax.numpy as jnp
    n, chunk = 1000, 128
    x = np.arange(n, dtype=np.float64)
    slices = chunk_rows(n, chunk)
    fn = jax.jit(lambda v: v * 2.0)
    pulled = {"n": 0}

    def gen():
        for sl in slices:
            pulled["n"] += 1
            yield sl

    computed = {"n": 0}
    window = []

    def compute(dev):
        computed["n"] += 1
        # bounded look-ahead: at the i-th compute, the source has
        # yielded at most i chunks plus the one-ahead stage
        window.append(pulled["n"] - computed["n"])
        return fn(dev)

    out = np.empty(n)

    def consume(i, sl, host):
        out[sl] = host
        return i

    order = stream(gen(), compute=compute,
                   put=lambda sl: jax.device_put(jnp.asarray(x[sl])),
                   consume=consume)
    assert order == list(range(len(slices)))
    assert np.array_equal(out, x * 2.0)
    assert max(window) <= 1        # never more than one chunk ahead
    # an exhausted-immediately generator is the empty stream
    assert stream((s for s in []), compute=fn) == []


def test_donate_jit_cpu_gating():
    """On CPU the wrapper must NOT request donation (the backend
    ignores it and warns per launch) — the same buffer stays usable
    across launches."""
    import jax
    import jax.numpy as jnp
    import warnings
    assert jax.devices()[0].platform == "cpu"
    fn = donate_jit(lambda v: v + 1.0, donate_argnums=(0,))
    buf = jnp.arange(4.0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a donation warning would raise
        a = fn(buf)
        b = fn(buf)                     # buffer NOT invalidated on cpu
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_stream_fault_propagates(fault_plan):
    """An injected fault on the worker thread surfaces to the caller
    (no hang, no silently dropped chunk); once the plan is exhausted
    the same pipeline runs clean."""
    import jax
    import jax.numpy as jnp
    fn = jax.jit(lambda v: v * 3.0)
    chunks = [jnp.ones(8) * i for i in range(4)]
    fault_plan("seed=7;site=pipeline.fetch,fails=1")
    with pytest.raises(InjectedFault):
        stream(chunks, compute=fn)
    # plan exhausted -> the identical pipeline now completes in order
    outs = stream(chunks, compute=fn)
    for i, o in enumerate(outs):
        assert np.array_equal(o, np.ones(8) * i * 3.0)


def test_streamed_pip_join_matches_unstreamed(grid):
    """The chunked double-buffered join returns the same zones as the
    one-launch join + host recheck (chunking must not change results,
    including at a ragged final chunk)."""
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn, localize,
                                              make_pip_join_fn,
                                              make_streamed_pip_join)
    arr = read_wkt(
        ["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))",
         "POLYGON ((8.5 1.5, 14.5 1.5, 14.5 6.5, 8.5 6.5, 8.5 1.5))"])
    chips = tessellate(arr, 1, grid)
    idx = build_pip_index(arr, 1, grid, chips=chips)
    rng = np.random.default_rng(11)
    pts = rng.uniform(0, 16, (10_000 + 37, 2))   # ragged last chunk
    join = jax.jit(make_pip_join_fn(idx, grid))
    z, u = join(jnp.asarray(localize(idx, pts)))
    ref = host_recheck_fn(idx, arr)(pts, np.asarray(z).copy(),
                                    np.asarray(u))
    sjoin = make_streamed_pip_join(idx, grid, polys=arr, chunk=2048)
    zs, rechecked = sjoin(pts)
    assert np.array_equal(zs, ref)
    assert rechecked >= 0
