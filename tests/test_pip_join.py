"""PIP-join pipeline: single-device and sharded paths vs host float64.

Reference workload: Quickstart PIP join (SURVEY.md §3.2 downstream join);
distribution testing mirrors the reference's local-cluster pattern
(test/SparkSuite.scala local[4]) with the 8-device CPU mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.bench.workloads import build_workload, nyc_points
from mosaic_tpu.parallel.pip_join import (build_pip_index, host_recheck,
                                          localize, make_pip_join_fn,
                                          make_sharded_pip_join,
                                          make_sharded_streamed_pip_join,
                                          make_streamed_pip_join,
                                          pip_host_truth,
                                          zone_histogram)


@pytest.fixture(scope="module")
def workload():
    polys, grid, res = build_workload(n_side=6, res_cells=64)
    idx = build_pip_index(polys, res, grid)
    return polys, grid, res, idx


def _mesh4():
    """4-device mesh carved from the 8 virtual host devices the suite
    pins via XLA_FLAGS (conftest.py) — the ISSUE's multichip-test
    shape without a second process config."""
    return jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))


def test_pip_join_matches_host_f64(workload):
    polys, grid, res, idx = workload
    pts64 = nyc_points(20_000, seed=3)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    zone, unc = fn(jnp.asarray(localize(idx, pts64)))
    zone = host_recheck(pts64, np.asarray(zone), np.asarray(unc), polys)
    truth = pip_host_truth(pts64, polys)
    assert np.array_equal(zone, truth)
    # a partition: everything except boundary-degenerate points matches
    assert np.mean(truth >= 0) > 0.999


def test_pip_join_partition_covers(workload):
    polys, grid, res, idx = workload
    # every cell of the bbox is core or border of some zone
    assert len(idx.core_cells) > 0 and idx.num_chips > 0
    assert idx.max_dup >= 2          # shared boundary cells exist


def test_out_of_domain_points(workload):
    polys, grid, res, idx = workload
    fn = jax.jit(make_pip_join_fn(idx, grid))
    pts = np.array([[-80.0, 40.7], [-74.0, 50.0], [0.0, 0.0]])
    zone, unc = fn(jnp.asarray(localize(idx, pts)))
    assert np.all(np.asarray(zone) == -1)


def test_sharded_pip_join(workload):
    polys, grid, res, idx = workload
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    fn = make_sharded_pip_join(idx, grid, mesh)
    pts64 = nyc_points(8 * 512, seed=5)
    zone, unc = fn(jnp.asarray(localize(idx, pts64)))
    ref_fn = jax.jit(make_pip_join_fn(idx, grid))
    zone1, unc1 = ref_fn(jnp.asarray(localize(idx, pts64)))
    assert np.array_equal(np.asarray(zone), np.asarray(zone1))
    hist = zone_histogram(zone, len(polys))
    assert int(hist.sum()) == int(np.sum(np.asarray(zone) >= 0))


def test_sharded_streamed_parity(workload):
    """The sharded streamed flagship path (bucketed padding + slot
    placement + mesh sharding) is bit-for-bit the single-device
    streamed join, including a ragged final chunk not divisible by
    the device count."""
    polys, grid, res, idx = workload
    pts64 = nyc_points(10_037, seed=9)    # 3 chunks, ragged tail
    ref = make_streamed_pip_join(idx, grid, polys=polys, chunk=4096)
    shj = make_sharded_streamed_pip_join(idx, grid, _mesh4(),
                                         polys=polys, chunk=4096)
    z_ref, r_ref = ref(pts64)
    z_sh, r_sh = shj(pts64)
    assert np.array_equal(z_sh, z_ref)
    assert r_sh == r_ref
    assert np.array_equal(z_ref, pip_host_truth(pts64, polys))


def _skewed_cloud(polys, n=4096, frac=0.9, seed=21):
    """90% of points uniform inside zone 0's box, 10% just west of the
    workload bbox (unmatched, zone -1), cluster-first row order — the
    worst case for contiguous row-order sharding."""
    rng = np.random.default_rng(seed)
    x0, y0, x1, y1 = polys.bboxes()[0]
    n_hot = int(n * frac)
    hot = np.stack([rng.uniform(x0, x1, n_hot),
                    rng.uniform(y0, y1, n_hot)], -1)
    wx0 = float(polys.bboxes()[:, 0].min())   # workload west edge
    cold = np.stack([rng.uniform(wx0 - 0.2, wx0 - 0.05, n - n_hot),
                     rng.uniform(y0, y1, n - n_hot)], -1)
    return np.concatenate([hot, cold])


def test_skew_rebalance_cuts_shard_load(workload):
    """A deliberately skewed cloud: with arrival-order placement three
    shards hold only matched rows while the last holds every
    unmatched one; once the SkewRebalancer arms (refresh=2), the
    greedy placement spreads the hot zone's bins and the observed
    per-shard matched skew drops to ~1.0 (acceptance: <= 1.5) without
    changing a single output zone."""
    from mosaic_tpu.obs import metrics
    polys, grid, res, idx = workload
    pts64 = _skewed_cloud(polys)
    shj = make_sharded_streamed_pip_join(
        idx, grid, _mesh4(), polys=polys, chunk=len(pts64), refresh=2)
    ref = make_streamed_pip_join(idx, grid, polys=polys,
                                 chunk=len(pts64))
    z_ref, _ = ref(pts64)
    assert np.mean(z_ref >= 0) == pytest.approx(0.9, abs=0.02)
    was = metrics.enabled
    metrics.enable()
    try:
        z0, _ = shj(pts64)
        pre = metrics.gauge_value("shard/skew/pip_join")
        assert not shj.rebalancer.armed
        assert pre == pytest.approx(1.0 / 0.9, rel=0.02)
        z1, _ = shj(pts64)               # obs 2 of 2 -> rebalance
        assert shj.rebalancer.armed
        z2, _ = shj(pts64)               # first placed run
        post = metrics.gauge_value("shard/skew/pip_join")
    finally:
        if not was:
            metrics.disable()
    assert post <= 1.5
    assert post < pre
    assert shj.rebalancer.planned_skew() <= 1.5
    # rebalancing moves rows between devices, never changes results
    for z in (z0, z1, z2):
        assert np.array_equal(z, z_ref)


def test_greedy_bin_packing_balances_density():
    """Unit-level packing claim: 90% of density clustered in one
    corner quarter of the bin lattice loads contiguous-block
    placement ~2x over mean; the greedy desc-density pack lands
    within the 1.5 acceptance bound."""
    from mosaic_tpu.parallel.placement import SkewRebalancer
    rng = np.random.default_rng(5)
    n = 20_000
    n_hot = int(n * 0.9)
    hot = rng.uniform(0.0, 0.25, (n_hot, 2))      # corner quarter
    cold = rng.uniform(0.0, 1.0, (n - n_hot, 2))
    pts = np.concatenate([hot, cold])
    r = SkewRebalancer(4, refresh=1, nbins=8)
    r.observe(pts, np.ones(n, bool))              # arms immediately
    assert r.armed
    assert r.contiguous_skew() > 1.5
    assert r.planned_skew() <= 1.5
    assert r.planned_skew() < r.contiguous_skew()
    pref = r.preferred(pts)
    assert pref.shape == (n,) and set(np.unique(pref)) <= set(range(4))


def test_placement_slots_properties():
    from mosaic_tpu.parallel.placement import placement_slots
    # identity when no preference is known yet
    assert np.array_equal(placement_slots(None, 5, 4, 2), np.arange(5))
    # preferences honored up to capacity, overflow spills, all slots
    # unique and within the padded buffer
    pref = np.array([0, 0, 0, 0, 2, 2, 1])
    slots = placement_slots(pref, len(pref), 4, 2)
    assert len(np.unique(slots)) == len(pref)
    assert slots.min() >= 0 and slots.max() < 4 * 2
    shard = slots // 2
    assert np.bincount(shard, minlength=4).max() <= 2
    # rows preferring shard 2 fit under its capacity and stay there
    assert np.all(shard[4:6] == 2)
    with pytest.raises(ValueError):
        placement_slots(pref, 9, 4, 2)


def test_sharded_skew_refresh_conf_key(workload):
    """Satellite: the monolithic sharded wrapper re-reads the skew on
    the mosaic.shard.skew.refresh cadence (a time series), not just
    on call 1."""
    from mosaic_tpu import config as cfgmod
    from mosaic_tpu.obs import metrics
    polys, grid, res, idx = workload
    # conf-key plumbing
    cfg = cfgmod.apply_conf(cfgmod.MosaicConfig(),
                            "mosaic.shard.skew.refresh", "8")
    assert cfg.shard_skew_refresh == 8
    with pytest.raises(cfgmod.ConfigError):
        cfgmod.apply_conf(cfgmod.MosaicConfig(),
                          "mosaic.shard.skew.refresh", "0")
    old = cfgmod.default_config()
    was = metrics.enabled
    metrics.enable()
    h = metrics.histogram("shard/skew_series/pip_join")
    before = h.count if h else 0
    try:
        cfgmod.set_default_config(
            dataclasses.replace(old, shard_skew_refresh=2))
        fn = make_sharded_pip_join(idx, grid, _mesh4())
        pts = jnp.asarray(localize(idx, nyc_points(4096, seed=13)))
        for _ in range(5):
            fn(pts)
    finally:
        cfgmod.set_default_config(old)
        if not was:
            metrics.disable()
    h = metrics.histogram("shard/skew_series/pip_join")
    # calls 0, 2, 4 hit the cadence -> exactly 3 new series points
    assert h is not None and h.count - before == 3


def test_coarse_res_continental_join_exact():
    """Continent-extent join at a COARSE resolution: the gap between
    the true gnomonic cell boundary (which assigns points) and the
    straight lon/lat chords the chips are clipped against is ~0.3 deg
    at res 2 — points inside that band must flag for the host pass
    instead of silently dropping (round-4: 7/20k points got zone -1
    while being degrees inside the polygon)."""
    import jax
    import mosaic_tpu as mos
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn,
                                              localize,
                                              make_pip_join_fn,
                                              pip_host_truth)
    grid = mos.get_index_system("H3")
    wide = mos.read_wkt(
        ["POLYGON ((-120 30, -70 30, -70 50, -120 50, -120 30))"])
    idx = build_pip_index(wide, 2, grid)
    rng = np.random.default_rng(0)
    pts = np.stack([rng.uniform(-121, -69, 20000),
                    rng.uniform(29, 51, 20000)], -1)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    zone, unc = fn(localize(idx, pts))
    zone = host_recheck_fn(idx, wide)(pts, np.asarray(zone).copy(),
                                      np.asarray(unc))
    assert np.array_equal(zone, pip_host_truth(pts, wide))
    # the exact per-workload sagitta keeps the band a small fraction
    # at mid latitudes (~4% here: 2x0.022 deg band along every cell
    # edge of ~3.5 deg cells, plus the chip-edge eps flags)
    assert np.asarray(unc).mean() < 0.10

    # high-latitude box: the chord-vs-gnomonic deviation there is tens
    # of times larger (the sampled global bound used to miss it —
    # round-4 review found 2-37 unflagged wrong-zone points per 20k)
    polar = mos.read_wkt(
        ["POLYGON ((-30 55, 30 55, 30 75, -30 75, -30 55))"])
    idx2 = build_pip_index(polar, 2, grid)
    pts2 = np.stack([rng.uniform(-31, 31, 20000),
                     rng.uniform(54, 76, 20000)], -1)
    fn2 = jax.jit(make_pip_join_fn(idx2, grid))
    z2, u2 = fn2(localize(idx2, pts2))
    z2 = host_recheck_fn(idx2, polar)(pts2, np.asarray(z2).copy(),
                                      np.asarray(u2))
    assert np.array_equal(z2, pip_host_truth(pts2, polar))
