"""PIP-join pipeline: single-device and sharded paths vs host float64.

Reference workload: Quickstart PIP join (SURVEY.md §3.2 downstream join);
distribution testing mirrors the reference's local-cluster pattern
(test/SparkSuite.scala local[4]) with the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mosaic_tpu.bench.workloads import build_workload, nyc_points
from mosaic_tpu.parallel.pip_join import (build_pip_index, host_recheck,
                                          localize, make_pip_join_fn,
                                          make_sharded_pip_join,
                                          pip_host_truth,
                                          zone_histogram)


@pytest.fixture(scope="module")
def workload():
    polys, grid, res = build_workload(n_side=6, res_cells=64)
    idx = build_pip_index(polys, res, grid)
    return polys, grid, res, idx


def test_pip_join_matches_host_f64(workload):
    polys, grid, res, idx = workload
    pts64 = nyc_points(20_000, seed=3)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    zone, unc = fn(jnp.asarray(localize(idx, pts64)))
    zone = host_recheck(pts64, np.asarray(zone), np.asarray(unc), polys)
    truth = pip_host_truth(pts64, polys)
    assert np.array_equal(zone, truth)
    # a partition: everything except boundary-degenerate points matches
    assert np.mean(truth >= 0) > 0.999


def test_pip_join_partition_covers(workload):
    polys, grid, res, idx = workload
    # every cell of the bbox is core or border of some zone
    assert len(idx.core_cells) > 0 and idx.num_chips > 0
    assert idx.max_dup >= 2          # shared boundary cells exist


def test_out_of_domain_points(workload):
    polys, grid, res, idx = workload
    fn = jax.jit(make_pip_join_fn(idx, grid))
    pts = np.array([[-80.0, 40.7], [-74.0, 50.0], [0.0, 0.0]])
    zone, unc = fn(jnp.asarray(localize(idx, pts)))
    assert np.all(np.asarray(zone) == -1)


def test_sharded_pip_join(workload):
    polys, grid, res, idx = workload
    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
    fn = make_sharded_pip_join(idx, grid, mesh)
    pts64 = nyc_points(8 * 512, seed=5)
    zone, unc = fn(jnp.asarray(localize(idx, pts64)))
    ref_fn = jax.jit(make_pip_join_fn(idx, grid))
    zone1, unc1 = ref_fn(jnp.asarray(localize(idx, pts64)))
    assert np.array_equal(np.asarray(zone), np.asarray(zone1))
    hist = zone_histogram(zone, len(polys))
    assert int(hist.sum()) == int(np.sum(np.asarray(zone) >= 0))


def test_coarse_res_continental_join_exact():
    """Continent-extent join at a COARSE resolution: the gap between
    the true gnomonic cell boundary (which assigns points) and the
    straight lon/lat chords the chips are clipped against is ~0.3 deg
    at res 2 — points inside that band must flag for the host pass
    instead of silently dropping (round-4: 7/20k points got zone -1
    while being degrees inside the polygon)."""
    import jax
    import mosaic_tpu as mos
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn,
                                              localize,
                                              make_pip_join_fn,
                                              pip_host_truth)
    grid = mos.get_index_system("H3")
    wide = mos.read_wkt(
        ["POLYGON ((-120 30, -70 30, -70 50, -120 50, -120 30))"])
    idx = build_pip_index(wide, 2, grid)
    rng = np.random.default_rng(0)
    pts = np.stack([rng.uniform(-121, -69, 20000),
                    rng.uniform(29, 51, 20000)], -1)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    zone, unc = fn(localize(idx, pts))
    zone = host_recheck_fn(idx, wide)(pts, np.asarray(zone).copy(),
                                      np.asarray(unc))
    assert np.array_equal(zone, pip_host_truth(pts, wide))
    # the exact per-workload sagitta keeps the band a small fraction
    # at mid latitudes (~4% here: 2x0.022 deg band along every cell
    # edge of ~3.5 deg cells, plus the chip-edge eps flags)
    assert np.asarray(unc).mean() < 0.10

    # high-latitude box: the chord-vs-gnomonic deviation there is tens
    # of times larger (the sampled global bound used to miss it —
    # round-4 review found 2-37 unflagged wrong-zone points per 20k)
    polar = mos.read_wkt(
        ["POLYGON ((-30 55, 30 55, 30 75, -30 75, -30 55))"])
    idx2 = build_pip_index(polar, 2, grid)
    pts2 = np.stack([rng.uniform(-31, 31, 20000),
                     rng.uniform(54, 76, 20000)], -1)
    fn2 = jax.jit(make_pip_join_fn(idx2, grid))
    z2, u2 = fn2(localize(idx2, pts2))
    z2 = host_recheck_fn(idx2, polar)(pts2, np.asarray(z2).copy(),
                                      np.asarray(u2))
    assert np.array_equal(z2, pip_host_truth(pts2, polar))
