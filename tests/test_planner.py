"""Cost-based adaptive planner tests.

The planner (sql/planner.py) is a pure strategy transform: it may only
change WHERE/HOW an operator runs, never what it returns.  These tests
pin (a) that invariant end-to-end across forced strategies, (b) the
learned-coefficient feedback loop (decisions flip when the observed
costs flip; estimate error converges after repeated runs), (c) the
persistence contract (warm start, versioned schema, corrupt-file
degrade-not-die), and (d) the conf-key surface + OpenMetrics export.
"""

import json

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.config import ConfigError
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.openmetrics import to_openmetrics
from mosaic_tpu.sql import SQLSession
from mosaic_tpu.sql.engine import _vectorized_equi_join
from mosaic_tpu.sql.planner import (MISPREDICT_FACTOR, STATS_VERSION,
                                    Decision, Planner, planner)


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture(scope="module")
def session(mc):
    return SQLSession(mc)


@pytest.fixture()
def clean_config():
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


# --------------------------------------------------------- conf keys


def test_conf_keys_validate():
    cfg = _config.MosaicConfig()
    cfg = _config.apply_conf(cfg, "mosaic.stream.chunk.rows", "65536")
    assert cfg.stream_chunk_rows == 65536
    for bad in ("abc", "0", "-4"):
        with pytest.raises(ConfigError):
            _config.apply_conf(cfg, "mosaic.stream.chunk.rows", bad)
    for ok in ("auto", "brute", "ring", "2048"):
        assert _config.apply_conf(
            cfg, "mosaic.knn.strategy", ok).knn_strategy == ok
    with pytest.raises(ConfigError):
        _config.apply_conf(cfg, "mosaic.knn.strategy", "bogus")
    cfg = _config.apply_conf(cfg, "mosaic.planner.enabled", "false")
    assert cfg.planner_enabled is False
    cfg = _config.apply_conf(cfg, "mosaic.planner.stats.path",
                             "/tmp/ps.json")
    assert cfg.planner_stats_path == "/tmp/ps.json"


def test_planner_force_keys():
    cfg = _config.MosaicConfig()
    cfg = _config.apply_conf(cfg, "mosaic.planner.force.equi_join",
                             "loop")
    assert _config.planner_force_for(cfg, "equi_join") == "loop"
    assert _config.planner_force_for(cfg, "knn") == "auto"
    # "auto" clears the pin
    cfg = _config.apply_conf(cfg, "mosaic.planner.force.equi_join",
                             "auto")
    assert _config.planner_force_for(cfg, "equi_join") == "auto"
    with pytest.raises(ConfigError):
        _config.apply_conf(cfg, "mosaic.planner.force.bogus_op",
                           "loop")
    with pytest.raises(ConfigError):
        _config.apply_conf(cfg, "mosaic.planner.force.knn",
                           "warp_drive")


def test_force_pins_decision(clean_config):
    _config.set_default_config(_config.apply_conf(
        _config.default_config(), "mosaic.planner.force.equi_join",
        "loop"))
    d = Planner().decide_equi_join(1 << 20, 1 << 10)
    assert d.strategy == "loop" and d.forced


# ------------------------------------------------- cost model mechanics


def test_cold_heuristics():
    p = Planner()
    assert p.decide_equi_join(100, 100).strategy == "loop"
    assert p.decide_equi_join(1 << 16, 1 << 10).strategy == \
        "vectorized"
    d = p.decide_pip_join(100)
    assert d.strategy == "monolithic"
    big = p.chunk_rows() * 4
    assert p.decide_pip_join(big).strategy == "streamed"
    assert p.decide_knn(50, 64, default_max=128).strategy == "brute"
    assert p.decide_knn(50, 10_000, default_max=128).strategy == "ring"


def test_learned_costs_flip_strategy():
    """The deterministic feedback loop: feed observed wall times and
    the decision follows whichever strategy measured cheaper."""
    p = Planner()
    n = 8192
    p.observe_op("equi_join/loop", n, 0.100)        # 100 ms
    p.observe_op("equi_join/vectorized", n, 0.002)  # 2 ms
    assert p.decide_equi_join(n // 2, n // 2).strategy == "vectorized"
    for _ in range(8):  # EWMA needs a few samples to cross over
        p.observe_op("equi_join/loop", n, 0.001)
        p.observe_op("equi_join/vectorized", n, 0.300)
    assert p.decide_equi_join(n // 2, n // 2).strategy == "loop"


def test_nearest_bucket_fallback_and_cap():
    p = Planner()
    p.observe_op("knn/brute", 1024, 0.010)
    # a coefficient learned at 1k rows still informs an 8k estimate
    assert p.ms_per_row("knn/brute", 8192) is not None
    assert p.ms_per_row("knn/ring", 8192) is None
    # the store is bounded (LRU): flooding it never grows past the cap
    for i in range(3000):
        p.observe_op(f"op{i}", 64, 0.001)
    assert p.report()["ms_keys"] <= 1024


def test_estimate_error_and_mispredicts():
    p = Planner()
    assert p.observe_estimate("filter", 100, 100) == 1.0
    assert p.observe_estimate("filter", 100, 400) > MISPREDICT_FACTOR
    assert p.mispredicts == 1
    assert p.error_p95() > 1.0


# ------------------------------------------------------- persistence


def test_warm_start_roundtrip(tmp_path):
    path = str(tmp_path / "stats.json")
    p = Planner()
    p.observe_op("pip_join/streamed/c16", 32768, 0.050, rows_out=900)
    assert p.save(path) == path
    blob = json.load(open(path))
    assert blob["version"] == STATS_VERSION
    # a fresh process (fresh Planner) plans from the saved coefficients
    p2 = Planner(stats_path=path)
    got = p2.ms_per_row("pip_join/streamed/c16", 32768)
    assert got == pytest.approx(0.050 * 1e3 / 32768)
    assert p2.ratio("pip_join/streamed/c16", 32768) == \
        pytest.approx(900 / 32768)


def test_corrupt_stats_degrade_not_die(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("{not json!!")
    p = Planner(stats_path=str(bad))   # must not raise
    assert p.ms_per_row("pip_join/monolithic", 100) is None
    wrong = tmp_path / "wrong.json"
    wrong.write_text(json.dumps({"version": 99, "ms_per_row": {}}))
    p2 = Planner(stats_path=str(wrong))
    assert p2.report()["ms_keys"] == 0
    # missing file: silently cold, and save() creates parent dirs
    p3 = Planner(stats_path=str(tmp_path / "sub" / "new.json"))
    p3.observe_op("knn/ring", 128, 0.001)
    assert p3.save() is not None


def test_stats_path_resolution(tmp_path, monkeypatch):
    path = str(tmp_path / "env.json")
    Planner(stats_path=path).save(path)
    monkeypatch.setenv("MOSAIC_TPU_PLANNER_STATS", path)
    p = Planner()
    assert p.configure_stats() == path  # env var wins over conf


# ------------------------------------------- pure strategy transform


def test_vectorized_join_matches_loop_reference(rng):
    """The sort-join must emit the exact pair sequence of the dict
    loop: left ascending, right index-ascending within each key."""
    for n, m, hi in [(50, 40, 8), (500, 300, 50), (1000, 1000, 2000)]:
        lk = rng.integers(0, hi, n)
        rk = rng.integers(0, hi, m)
        li, ri = _vectorized_equi_join(lk, rk)
        rmap = {}
        for j, k in enumerate(rk.tolist()):
            rmap.setdefault(k, []).append(j)
        eli, eri = [], []
        for i, k in enumerate(lk.tolist()):
            for j in rmap.get(k, ()):
                eli.append(i)
                eri.append(j)
        assert li.tolist() == eli
        assert ri.tolist() == eri


def test_forced_strategies_bit_identical(session, clean_config):
    rng = np.random.default_rng(3)
    n = 5000
    session.create_table("pl", {
        "k": rng.integers(0, 200, n).astype(np.int64),
        "v": rng.random(n)})
    session.create_table("pr", {
        "k": np.arange(200, dtype=np.int64),
        "w": rng.random(200)})
    q = ("SELECT pl.k AS k, v, w FROM pl JOIN pr ON pl.k = pr.k "
         "ORDER BY v LIMIT 500")
    outs = {}
    for strat in ("loop", "vectorized"):
        _config.set_default_config(_config.apply_conf(
            _config.default_config(),
            "mosaic.planner.force.equi_join", strat))
        outs[strat] = session.sql(q)
    for col in outs["loop"].columns:
        assert np.array_equal(outs["loop"].columns[col],
                              outs["vectorized"].columns[col]), col


def test_vectorized_ineligible_keys_fall_back(session, clean_config):
    """NaN float keys and composite keys are outside the sort-join's
    equality semantics — a forced "vectorized" pick must fall back to
    the loop and still return the loop's exact rows."""
    session.create_table("nl", {
        "k": np.array([1.0, np.nan, 2.0, np.nan, 3.0]),
        "a": np.arange(5.0)})
    session.create_table("nr", {
        "k": np.array([np.nan, 2.0, 3.0, 1.0]),
        "b": np.arange(4.0)})
    session.create_table("cl", {
        "k1": np.array([1, 1, 2, 2], np.int64),
        "k2": np.array([0, 1, 0, 1], np.int64),
        "a": np.arange(4.0)})
    session.create_table("cr", {
        "k1": np.array([2, 1], np.int64),
        "k2": np.array([1, 1], np.int64),
        "b": np.array([10.0, 20.0])})
    queries = [
        "SELECT a, b FROM nl JOIN nr ON nl.k = nr.k ORDER BY a",
        "SELECT a, b FROM cl JOIN cr ON cl.k1 = cr.k1 "
        "AND cl.k2 = cr.k2 ORDER BY a",
    ]
    outs = {}
    for strat in ("loop", "vectorized"):
        _config.set_default_config(_config.apply_conf(
            _config.default_config(),
            "mosaic.planner.force.equi_join", strat))
        outs[strat] = [session.sql(q) for q in queries]
    for a, b in zip(outs["loop"], outs["vectorized"]):
        for col in a.columns:
            assert np.array_equal(a.columns[col], b.columns[col]), col
    # NaN keys never match (dict-loop semantics preserved)
    assert len(outs["loop"][0]) == 3


def test_planner_off_bit_identical(session, clean_config):
    rng = np.random.default_rng(11)
    n = 6000
    session.create_table("po", {
        "k": rng.integers(0, 64, n).astype(np.int64),
        "v": rng.random(n)})
    q = ("SELECT k, count(*) AS c, sum(v) AS s FROM po "
         "WHERE v > 0.5 GROUP BY k ORDER BY k")
    on = session.sql(q)
    _config.set_default_config(_config.apply_conf(
        _config.default_config(), "mosaic.planner.enabled", "false"))
    off = session.sql(q)
    for col in on.columns:
        assert np.array_equal(on.columns[col], off.columns[col]), col


# --------------------------------------------------- feedback loop


def test_estimate_error_converges_after_three_runs(mc):
    """The acceptance bar: running the same workload 3 times, the
    estimate-error p95 over the LAST run's closed estimates is < 2x
    (the planner learned the workload's selectivities/fanouts)."""
    planner.reset()
    rng = np.random.default_rng(5)
    n = 4000

    def run_workload():
        s = SQLSession(mc)
        s.create_table("wl", {
            "k": rng.integers(0, 100, n).astype(np.int64),
            "v": rng.random(n)})
        s.create_table("wr", {
            "k": np.arange(100, dtype=np.int64),
            "w": rng.random(100)})
        s.sql("SELECT k, v FROM wl WHERE v > 0.75 ORDER BY v")
        s.sql("SELECT wl.k AS k, v, w FROM wl JOIN wr ON wl.k = wr.k")
        s.sql("SELECT k, count(*) AS c FROM wl GROUP BY k")

    run_workload()
    run_workload()
    before = len(planner.error_history)
    run_workload()
    last_run = list(planner.error_history)[before:]
    assert last_run, "third run closed no estimates"
    p95 = float(np.percentile(last_run, 95))
    assert p95 < MISPREDICT_FACTOR, last_run
    assert planner.report()["decisions"] > 0


# ------------------------------------------------------ observability


def test_planner_metrics_in_openmetrics():
    was = metrics.enabled
    metrics.enable()
    try:
        planner.record_decision(Decision(
            "pip_join", "streamed", "test", 100, key_n=100))
        planner.observe_estimate("pip_join", 100, 90)
        text = to_openmetrics()
        assert "mosaic_planner_decisions_total" in text
        assert "mosaic_planner_estimate_error" in text
    finally:
        if not was:
            metrics.disable()
