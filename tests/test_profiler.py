"""The continuous profiling plane (``obs.profiler``).

Covers the acceptance surface of the profiling PR: host-sampler
lifecycle (no leaked threads), collapsed-stack correctness on a
synthetic workload, per-trace attribution with two interleaved SQL
queries, the kernel ledger joined against a warm streamed join, the
breach drill producing a flight bundle with a non-empty profile, the
shared dump cooldown, the recorder ring drop counter, speedscope
export shape, conf validation, ``device_trace``, and the dashboard's
``/api/profile`` + ``/profile`` routes.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics, new_trace, recorder, tracer
from mosaic_tpu.obs.profiler import (DEFAULT_PROFILE_HZ, HostProfiler,
                                     KernelLedger, capture_snapshot,
                                     configure_profiler, ledger,
                                     maybe_device_capture, profiler,
                                     start_profiler, stop_profiler)


@pytest.fixture
def clean_obs():
    recorder.reset()
    recorder.enable()
    metrics.reset()
    metrics.enable()
    ledger.reset()
    yield
    stop_profiler()
    ledger.reset()
    metrics.disable()
    metrics.reset()
    recorder.reset()


@pytest.fixture
def clean_config():
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


@pytest.fixture
def session():
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    s = mos.SQLSession(ctx)
    s.create_table("pts", {"x": np.arange(100.0),
                           "y": np.arange(100.0) / 10.0})
    return s


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return r.read().decode("utf-8")


# ----------------------------------------------------- lifecycle

def test_sampler_lifecycle_no_leaked_threads(clean_obs):
    before = threading.active_count()
    p = start_profiler(hz=200.0)
    assert p.alive and profiler() is p
    assert threading.active_count() == before + 1
    time.sleep(0.05)
    stop_profiler()
    assert profiler() is None and not p.alive
    assert threading.active_count() == before
    # restart replaces, never stacks
    p2 = start_profiler(hz=100.0)
    p3 = start_profiler(hz=100.0)
    assert not p2.alive and p3.alive
    assert threading.active_count() == before + 1
    stop_profiler()
    assert threading.active_count() == before
    # lifecycle transitions landed in the flight recorder
    assert len(recorder.events("profiler")) == 3


def test_hz_is_clamped_and_recorded(clean_obs):
    assert HostProfiler(hz=0.0001).hz == 0.5
    assert HostProfiler(hz=1e9).hz == 1000.0
    assert HostProfiler().hz == DEFAULT_PROFILE_HZ


def test_configure_profiler_conf_lifecycle(clean_obs, monkeypatch):
    monkeypatch.delenv("MOSAIC_TPU_PROFILE_HZ", raising=False)
    configure_profiler(50.0)
    p = profiler()
    assert p is not None and p.hz == 50.0
    configure_profiler(50.0)                  # no change -> same thread
    assert profiler() is p
    configure_profiler(0.0)
    assert profiler() is None
    # env pin: conf values are ignored while the env var is set
    monkeypatch.setenv("MOSAIC_TPU_PROFILE_HZ", "123")
    configure_profiler(75.0)
    assert profiler() is None


def test_profile_hz_conf_validation(clean_config):
    cfg = _config.default_config()
    cfg = _config.apply_conf(cfg, "mosaic.obs.profile.hz", "97")
    assert cfg.obs_profile_hz == 97.0
    cfg = _config.apply_conf(cfg, "mosaic.obs.dump.cooldown.ms", 1000)
    assert cfg.obs_dump_cooldown_ms == 1000.0
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(cfg, "mosaic.obs.profile.hz", -5)
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(cfg, "mosaic.obs.profile.hz", "fast")


# ------------------------------------------------ collapsed stacks

def _busy_until(stop):
    def inner_hot():
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < 0.002:
            pass
    while not stop.is_set():
        inner_hot()


def test_collapsed_stack_correctness_synthetic(clean_obs):
    """Manual sample() passes over a known two-frame workload: the
    collapsed output must contain the root->leaf chain in order."""
    p = HostProfiler(hz=100.0)                # never started: inline
    stop = threading.Event()
    t = threading.Thread(target=_busy_until, args=(stop,), daemon=True)
    t.start()
    try:
        for _ in range(30):
            p.sample()
            time.sleep(0.002)
    finally:
        stop.set()
        t.join()
    assert p.samples == 30
    rep = p.report()
    assert rep["distinct_stacks"] >= 1 and rep["truncated"] == 0
    busy = [s for s in rep["stacks"]
            if s["frames"][-1].endswith(":inner_hot")]
    assert busy, f"no inner_hot stack in {rep['stacks']}"
    # root-first ordering: the caller precedes the leaf on the line
    line = [l for l in p.collapsed().splitlines()
            if ":inner_hot" in l][0]
    frames, _, count = line.rpartition(" ")
    assert int(count) >= 1
    assert frames.index(":_busy_until") < frames.index(":inner_hot")


def test_collapsed_respects_bounds(clean_obs):
    p = HostProfiler(max_stacks=1, max_depth=2)
    stop = threading.Event()
    t = threading.Thread(target=_busy_until, args=(stop,), daemon=True)
    t.start()
    try:
        for _ in range(10):
            p.sample()
            time.sleep(0.002)
    finally:
        stop.set()
        t.join()
    rep = p.report()
    assert rep["distinct_stacks"] <= 1
    assert all(len(s["frames"]) <= 2 for s in rep["stacks"])
    p.reset()
    assert p.report()["distinct_stacks"] == 0 and p.samples == 0


def test_speedscope_schema(clean_obs):
    p = HostProfiler()
    stop = threading.Event()
    t = threading.Thread(target=_busy_until, args=(stop,), daemon=True)
    t.start()
    try:
        for _ in range(10):
            p.sample()
            time.sleep(0.002)
    finally:
        stop.set()
        t.join()
    ss = p.speedscope()
    assert ss["$schema"].startswith("https://www.speedscope.app")
    prof = ss["profiles"][0]
    assert prof["type"] == "sampled"
    n_frames = len(ss["shared"]["frames"])
    assert prof["samples"] and len(prof["samples"]) == \
        len(prof["weights"])
    assert all(0 <= ix < n_frames
               for row in prof["samples"] for ix in row)
    assert prof["endValue"] == sum(prof["weights"])
    json.dumps(ss)                            # fully serializable


# -------------------------------------------- per-trace attribution

def test_two_interleaved_queries_get_disjoint_profiles(
        clean_obs, session, fault_plan):
    """Two SQL queries running concurrently (held open by a fault-plan
    delay) must sample into distinct trace ids, each carrying its own
    stacks — the attribution contract."""
    fault_plan("site=sql.query,mode=delay,fails=2,delay_ms=400")
    p = HostProfiler()
    errs = []

    def q():
        try:
            session.sql("SELECT x FROM pts")
        except Exception as e:                # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=q, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.time() + 2.0
    while any(t.is_alive() for t in threads) and time.time() < deadline:
        p.sample()
        time.sleep(0.01)
    for t in threads:
        t.join(timeout=5)
    assert not errs
    rep = p.report()
    sql_traces = {tid: info for tid, info in rep["traces"].items()
                  if info["name"].startswith("sql:")}
    assert len(sql_traces) == 2, rep["traces"]
    assert all(info["samples"] > 0 for info in sql_traces.values())
    # stack keys are disjoint by construction: each trace's filtered
    # view is non-empty and its counts add up to that trace's rollup
    t1, t2 = sql_traces
    assert p.collapsed(t1) and p.collapsed(t2)
    for tid in (t1, t2):
        counts = sum(s["count"] for s in rep["stacks"]
                     if s["trace"] == tid)
        assert counts == sql_traces[tid]["samples"] > 0


# ------------------------------------------------- kernel ledger

def test_ledger_accumulates_and_bounds(clean_obs):
    led = KernelLedger(max_entries=2)
    led.observe("k/a", (64,), 0.5, rows=100)
    led.observe("k/a", (64,), 0.25, rows=100)
    led.observe("k/b", (128,), 0.25, rows=50)
    led.observe("k/c", (256,), 1.0, rows=10)  # over capacity: dropped
    rep = led.report()
    assert [e["name"] for e in rep["kernels"]] == ["k/a", "k/b"]
    assert rep["kernels"][0]["launches"] == 2
    assert rep["kernels"][0]["seconds"] == 0.75
    assert rep["kernels"][0]["rows_per_s"] == round(200 / 0.75)
    assert rep["dropped"] == 1
    assert led.seconds("k/a") == 0.75
    assert led.seconds() == 1.0
    led.record_cost("k/a", {"flops": 2e9, "label": "ignored"})
    e = led.report()["kernels"][0]
    assert e["cost"] == {"flops": 2e9}
    assert e["gflops_s"] == pytest.approx(2 * 2e9 / 0.75 / 1e9, rel=.01)


def test_ledger_joins_warm_streamed_join(clean_obs):
    """The flagship-shaped join feeds the ledger: one pip/streamed
    entry, one launch per chunk, and the observed seconds cover most
    of the measured wall time (the bench asserts >= 0.9 on the real
    workload; the floor here is loose for CI noise on a tiny one)."""
    from mosaic_tpu import read_wkt
    from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              make_streamed_pip_join)
    grid = CustomIndexSystem(GridConf(0, 16, 0, 16, 2, 1.0, 1.0))
    arr = read_wkt(
        ["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))",
         "POLYGON ((8.5 1.5, 14.5 1.5, 14.5 6.5, 8.5 6.5, 8.5 1.5))"])
    idx = build_pip_index(arr, 1, grid,
                          chips=tessellate(arr, 1, grid))
    pts = np.random.default_rng(3).uniform(0, 16, (8192, 2))
    sjoin = make_streamed_pip_join(idx, grid, polys=arr, chunk=2048)
    sjoin(pts)                                # warm (compile)
    ledger.reset()
    t0 = time.perf_counter()
    sjoin(pts)
    wall = time.perf_counter() - t0
    rep = ledger.report()
    (e,) = [k for k in rep["kernels"] if k["name"] == "pip/streamed"]
    assert e["launches"] == 4                 # 8192 / 2048
    assert e["rows"] == 8192
    assert 0 < e["seconds"] <= wall * 1.05
    assert ledger.seconds("pip/streamed") >= 0.5 * wall
    # the jit cache seeded the entry name it registered under
    assert "pip/streamed" in {k["name"] for k in rep["kernels"]}


def test_jit_cache_registers_ledger_rows(clean_obs):
    from mosaic_tpu.perf.jit_cache import kernel_cache
    kernel_cache.get_or_build("test/ledger_seed", (7,), lambda: object)
    names = {k["name"] for k in ledger.report()["kernels"]}
    assert "test/ledger_seed" in names
    (e,) = [k for k in ledger.report()["kernels"]
            if k["name"] == "test/ledger_seed"]
    assert e["launches"] == 0                 # known, never observed


# --------------------------------------- triggered capture / bundles

def test_bundle_embeds_profile_snapshot(clean_obs):
    ledger.observe("k/x", (1,), 0.1)
    p = start_profiler(hz=200.0)
    stop = threading.Event()
    t = threading.Thread(target=_busy_until, args=(stop,), daemon=True)
    t.start()
    try:
        time.sleep(0.15)
    finally:
        stop.set()
        t.join()
    b = recorder.bundle(reason="test")
    assert b["dropped"] == 0
    prof = b["profile"]
    assert prof["collapsed"]                  # non-empty stacks
    assert prof["host"]["samples"] > 0
    assert [k["name"] for k in prof["ledger"]["kernels"]] == ["k/x"]
    stop_profiler()
    # snapshot stays well-formed with the sampler off
    snap = capture_snapshot()
    assert snap["collapsed"] == "" and snap["host"] == {}
    assert snap["ledger"]["kernels"]


def test_breach_drill_dump_contains_profile(
        clean_obs, clean_config, session, fault_plan, tmp_path,
        monkeypatch):
    """The acceptance drill: an SLO breach writes a flight bundle whose
    ``profile`` block carries non-empty collapsed stacks."""
    from mosaic_tpu.obs.slo import SLObjective, monitor
    from mosaic_tpu.obs.timeseries import timeseries
    monkeypatch.setenv("MOSAIC_TPU_DUMP_DIR", str(tmp_path))
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.obs.slo.dump", True)
    _config.set_default_config(cfg)
    timeseries.reset()
    monitor.reset([SLObjective(
        name="sql_latency", kind="latency", series="sql/query_ms",
        threshold_ms=250.0, objective=0.95, min_points=1,
        windows=(60.0, 300.0))])
    start_profiler(hz=300.0)
    try:
        fault_plan("site=sql.query,mode=delay,fails=1,delay_ms=500")
        session.sql("SELECT x FROM pts")      # sampled while stalled
        trans = monitor.evaluate()
        assert [t["transition"] for t in trans] == ["breach"]
    finally:
        stop_profiler()
        monitor.reset()
        timeseries.reset()
    dumps = list(tmp_path.glob("*_slo_sql_latency.json"))
    assert len(dumps) == 1
    b = json.loads(dumps[0].read_text())
    assert b["profile"]["collapsed"]
    assert b["profile"]["host"]["samples"] > 0


def test_maybe_device_capture_disabled_is_none(clean_obs, clean_config):
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.obs.profile.trace.ms", 0)
    _config.set_default_config(cfg)
    assert maybe_device_capture("test") is None


def test_device_trace_writes_logdir(tmp_path):
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.obs import device_trace
    logdir = tmp_path / "trace"
    try:
        with device_trace(str(logdir)):
            jax.block_until_ready(jnp.arange(8.0) * 2.0)
    except Exception as e:
        pytest.skip(f"jax.profiler unavailable here: {e}")
    assert logdir.exists() and any(logdir.rglob("*"))


# ------------------------------------------- cooldown + drop counter

def test_dump_cooldown_suppresses_and_flushes(
        clean_obs, clean_config, tmp_path, monkeypatch):
    monkeypatch.setenv("MOSAIC_TPU_DUMP_DIR", str(tmp_path))
    assert recorder.dump_throttled(reason="slow_query") is not None
    # inside the 30 s default cooldown: held, counted, evented
    assert recorder.dump_throttled(reason="slow_query") is None
    assert recorder.dump_throttled(reason="slo_x") is None
    sup = recorder.events("dump_suppressed")
    assert [e["suppressed"] for e in sup] == [1, 2]
    assert len(list(tmp_path.glob("*.json"))) == 1
    # cooldown 0 disables the gate; the flush event reports the count
    cfg = _config.apply_conf(_config.default_config(),
                             "mosaic.obs.dump.cooldown.ms", 0)
    _config.set_default_config(cfg)
    assert recorder.dump_throttled(reason="slow_query") is not None
    (fl,) = recorder.events("dump_suppressed_flush")
    assert fl["suppressed"] == 2
    assert len(list(tmp_path.glob("*.json"))) == 2


def test_recorder_ring_drop_counter(clean_obs):
    recorder.reset(capacity=16)           # 16 is the ring's floor
    try:
        for i in range(20):
            recorder.record("tick", i=i)
        assert len(recorder.events("tick")) == 16
        assert recorder.dropped == 4
        assert recorder.bundle()["dropped"] == 4
        assert metrics.counter_value("obs/recorder_dropped") == 4
    finally:
        recorder.reset(capacity=4096)
    assert recorder.dropped == 0


# --------------------------------------------------- dashboard

def test_dashboard_profile_routes(clean_obs, session):
    from mosaic_tpu.obs import serve_dashboard
    ledger.observe("pip/streamed", (64,), 0.2, rows=640)
    start_profiler(hz=200.0)
    stop = threading.Event()
    t = threading.Thread(target=_busy_until, args=(stop,), daemon=True)
    t.start()
    handle = serve_dashboard(port=0)
    base = f"http://127.0.0.1:{handle.port}"
    try:
        time.sleep(0.1)
        prof = json.loads(_get(base + "/api/profile"))
        assert prof["running"] is True
        assert prof["host"]["samples"] > 0
        assert prof["collapsed"]
        names = [k["name"] for k in prof["ledger"]["kernels"]]
        assert "pip/streamed" in names
        # trace filter: an unknown trace id yields an empty profile
        empty = json.loads(_get(base + "/api/profile?trace=t0-nope"))
        assert empty["collapsed"] == "" and empty["host"]["stacks"] == []
        page = _get(base + "/profile")
        assert "Flame graph" in page and "/api/profile" in page
        root = _get(base + "/")
        assert "/profile" in root
    finally:
        stop.set()
        t.join()
        handle.close()
        stop_profiler()
