"""Stable vector-form gnomonic projection (h3/hexmath.py, h3/jaxkernel.py).

The round-3 rewrite replaced the polar (arccos/atan2) projection whose
conditioning cost ~3 m of cell-assignment uncertainty.  These tests pin:
host vector form == host polar form; the device f64 path's margin
contract (every device/host cell disagreement is flagged by a margin
below err_lattice_bound); lattice→cell-id aggregation parity.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mosaic_tpu.core.index.h3 import hexmath as hm
from mosaic_tpu.core.index.h3 import index as ix
from mosaic_tpu.core.index.h3.jaxkernel import (cell_from_lattice_jax,
                                                err_lattice_bound,
                                                pick_precision,
                                                project_lattice_jax)


@pytest.fixture(scope="module")
def sphere_pts(rng=None):
    r = np.random.default_rng(9)
    n = 50_000
    lat = np.arcsin(r.uniform(-1, 1, n))
    lng = r.uniform(-np.pi, np.pi, n)
    return np.stack([lat, lng], axis=-1)


@pytest.mark.parametrize("res", [0, 5, 9, 15])
def test_host_vector_equals_polar(sphere_pts, res):
    f1, h1 = hm.geo_to_hex2d(sphere_pts, res)
    f2, h2 = hm.project_lattice(sphere_pts, res)
    assert np.array_equal(f1, f2)
    assert np.max(np.abs(h1 - h2)) / hm.M_SQRT7 ** res < 1e-9


def test_cpu_auto_precision_is_f64():
    assert pick_precision("auto") == "f64"


@pytest.mark.parametrize("res", [7, 9, 12])
def test_device_margin_contract_local(res):
    """f64 device path, origin-localized input: any cell disagreement
    with the host f64 truth must carry a margin below the bound."""
    r = np.random.default_rng(11)
    origin = np.array([-74.0, 40.7])
    n = 200_000
    loc = np.stack([r.uniform(-0.4, 0.4, n),
                    r.uniform(-0.3, 0.3, n)], -1)
    latlng = np.radians((loc + origin[None])[:, ::-1])
    fh, hex2d = hm.project_lattice(latlng, res)
    ijk = hm.hex2d_to_ijk(hex2d)
    ah, bh = ijk[:, 0] - ijk[:, 2], ijk[:, 1] - ijk[:, 2]

    fd, ad, bd, margin, gap = [np.asarray(v) for v in jax.jit(
        lambda p: project_lattice_jax(p, res, origin, precision="f64"))(
        jnp.asarray(loc, jnp.float32))]
    dis = ~((fd == fh) & (ad == ah) & (bd == bh))
    bound = err_lattice_bound(res, "f64", 0.4)
    assert not np.any(dis & (margin >= bound))


def test_lattice_aggregation_id_parity():
    """(face, a, b) -> cell id matches the host encoder end to end."""
    r = np.random.default_rng(13)
    n = 100_000
    lat = np.arcsin(r.uniform(-1, 1, n))
    lng = r.uniform(-np.pi, np.pi, n)
    latlng = np.stack([lat, lng], axis=-1)
    for res in (0, 3, 9):
        fh, hex2d = hm.project_lattice(latlng, res)
        ijk = hm.hex2d_to_ijk(hex2d)
        ah = (ijk[:, 0] - ijk[:, 2]).astype(np.int32)
        bh = (ijk[:, 1] - ijk[:, 2]).astype(np.int32)
        ids = np.asarray(jax.jit(
            lambda f, a, b: cell_from_lattice_jax(f, a, b, res))(
            jnp.asarray(fh.astype(np.int32)), jnp.asarray(ah),
            jnp.asarray(bh)))
        want = ix.latlng_to_cell(latlng, res)
        assert np.array_equal(ids, want)
