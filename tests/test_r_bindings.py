"""R bindings generator tests.

Reference counterpart: R/generate_R_bindings.R (build-time generation of
the sparkR/sparklyr packages from the Scala DSL) + its testthat suites.
No R runtime ships in this image, so the tests pin the generator's
contract: every registered function gets a wrapper, the generated
sources stay balanced/parseable, and the committed package is in
lock-step with the live registry.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GEN = os.path.join(REPO, "bindings", "r", "generate_r_bindings.py")
PKG = os.path.join(REPO, "bindings", "r", "rMosaicTpu")


@pytest.fixture(scope="module")
def generated(tmp_path_factory):
    out = tmp_path_factory.mktemp("rpkg")
    r = subprocess.run([sys.executable, GEN, str(out)],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    return str(out)


def test_every_registered_function_has_a_wrapper(generated):
    from mosaic_tpu.functions.registry import REGISTRY
    src = open(os.path.join(generated, "R", "functions.R")).read()
    wrapped = set(re.findall(r"^([A-Za-z_0-9]+) <- function", src,
                             re.MULTILINE))
    missing = set(REGISTRY) - wrapped
    assert not missing, f"no R wrapper for {sorted(missing)}"
    assert "enableMosaic" in wrapped


def test_generated_r_is_balanced(generated):
    for rel in (("R", "functions.R"),
                ("tests", "testthat", "test-functions.R")):
        src = open(os.path.join(generated, *rel)).read()
        for o, c in (("(", ")"), ("{", "}")):
            assert src.count(o) == src.count(c), \
                f"unbalanced {o}{c} in {'/'.join(rel)}"


def test_defaults_render_as_r_literals(generated):
    src = open(os.path.join(generated, "R", "functions.R")).read()
    # grid_tessellate(keep_core_geom=True) -> TRUE
    m = re.search(r"grid_tessellate <- function\(([^)]*)\)", src)
    assert m and "keep_core_geom = TRUE" in m.group(1)
    # st_buffer(cap_style="round") -> quoted string
    m = re.search(r"st_buffer <- function\(([^)]*)\)", src)
    assert m and 'cap_style = "round"' in m.group(1)


def test_package_metadata(generated):
    desc = open(os.path.join(generated, "DESCRIPTION")).read()
    assert "Package: rMosaicTpu" in desc and "reticulate" in desc
    ns = open(os.path.join(generated, "NAMESPACE")).read()
    assert "exportPattern" in ns and "enableMosaic" in ns


def test_committed_package_in_lockstep(generated):
    """The checked-in package must equal a fresh generation (the
    reference regenerates R sources on every build)."""
    for rel in (("R", "functions.R"), ("DESCRIPTION"), ("NAMESPACE")):
        rel = (rel,) if isinstance(rel, str) else rel
        fresh = open(os.path.join(generated, *rel)).read()
        committed = open(os.path.join(PKG, *rel)).read()
        assert fresh == committed, \
            f"{'/'.join(rel)} stale — rerun bindings/r/generate_r_bindings.py"
