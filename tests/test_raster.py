"""Raster subsystem: tile model, GeoTIFF codec, operators, pipeline.

Mirrors the reference's raster test strategy (SURVEY.md §4: hermetic
small synthetic fixtures, numpy oracles; reference fixtures live in
src/test/resources/binary/).  BASELINE config 5 in miniature lives in
TestRasterToGrid.
"""

import io

import numpy as np
import pytest

from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
from mosaic_tpu.core.raster import (GeoTransform, RasterTile, read_gtiff,
                                    write_gtiff)
from mosaic_tpu.core.raster import rops
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.io.raster_grid import raster_to_grid


@pytest.fixture(scope="module")
def ctx():
    return MosaicContext.build("CUSTOM(0,16,0,16,2,1,1)")


def dem_tile(rng, h=64, w=64, bands=1, nodata=-9999.0):
    data = rng.uniform(0, 1000, (bands, h, w)).astype(np.float32)
    gt = GeoTransform(0.0, 16.0 / w, 0.0, 16.0, 0.0, -16.0 / h)
    return RasterTile(data, gt, nodata=nodata, srid=4326)


class TestGeoTransform:
    def test_world_raster_roundtrip(self, rng):
        gt = GeoTransform(-74.3, 0.01, 0.0, 40.95, 0.0, -0.01)
        cols = rng.uniform(0, 100, 50)
        rows = rng.uniform(0, 100, 50)
        x, y = gt.to_world(cols, rows)
        c2, r2 = gt.to_raster(x, y)
        np.testing.assert_allclose(c2, cols, atol=1e-9)
        np.testing.assert_allclose(r2, rows, atol=1e-9)

    def test_rotated_inverse(self):
        gt = GeoTransform(10.0, 1.0, 0.2, 20.0, -0.1, -1.0)
        x, y = gt.to_world(3.0, 7.0)
        c, r = gt.to_raster(x, y)
        assert c == pytest.approx(3.0) and r == pytest.approx(7.0)


class TestCodec:
    @pytest.mark.parametrize("dtype", [np.uint8, np.uint16, np.int16,
                                       np.int32, np.float32, np.float64])
    @pytest.mark.parametrize("compress", [False, True])
    def test_roundtrip(self, rng, dtype, compress):
        d = rng.uniform(0, 100, (2, 33, 47)).astype(dtype)
        t = RasterTile(d, GeoTransform(-74.0, 1e-3, 0, 40.9, 0, -1e-3),
                       nodata=7.0, srid=4326)
        back = read_gtiff(write_gtiff(t, compress=compress))
        assert np.array_equal(back.data, d)
        assert back.gt.to_tuple() == pytest.approx(t.gt.to_tuple())
        assert back.nodata == 7.0
        assert back.srid == 4326

    def test_projected_srid_roundtrip(self, rng):
        d = rng.uniform(0, 10, (1, 8, 8)).astype(np.float32)
        t = RasterTile(d, GeoTransform(0, 10, 0, 0, 0, -10), srid=27700)
        assert read_gtiff(write_gtiff(t)).srid == 27700

    def test_pil_interop(self, rng):
        """Cross-decode TIFFs produced by an independent writer."""
        from PIL import Image
        arr = rng.uniform(0, 255, (21, 34)).astype(np.uint8)
        for comp in (None, "tiff_deflate", "packbits"):
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="TIFF",
                                      **({"compression": comp}
                                         if comp else {}))
            t = read_gtiff(buf.getvalue())
            assert np.array_equal(t.data[0], arr), comp

    def test_pil_predictor2_multiband(self, rng):
        """Horizontal differencing must undo per component, not across
        interleaved samples (regression)."""
        from PIL import Image
        arr = rng.integers(0, 255, (20, 30, 3)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="TIFF",
                                  compression="tiff_deflate",
                                  tiffinfo={317: 2})
        t = read_gtiff(buf.getvalue())
        assert np.array_equal(np.moveaxis(t.data, 0, -1), arr)

    def test_srid_out_of_geokey_range(self, rng):
        t = dem_tile(rng, 4, 4)
        import dataclasses
        t = dataclasses.replace(t, srid=900913)
        with pytest.raises(ValueError, match="SRID"):
            write_gtiff(t)

    def test_bad_input_raises(self):
        with pytest.raises(ValueError, match="TIFF"):
            read_gtiff(b"nope")
        with pytest.raises(ValueError, match="truncated"):
            read_gtiff(b"II")


class TestTile:
    def test_band_stats_respect_nodata(self, rng):
        d = np.array([[[1.0, 2.0], [-9999.0, 3.0]]], np.float32)
        t = RasterTile(d, GeoTransform(0, 1, 0, 0, 0, -1),
                       nodata=-9999.0)
        s = t.band_stats(0)
        assert s["count"] == 3 and s["min"] == 1.0 and s["max"] == 3.0

    def test_is_empty(self):
        d = np.full((1, 4, 4), -1.0, np.float32)
        t = RasterTile(d, GeoTransform(0, 1, 0, 0, 0, -1), nodata=-1.0)
        assert t.is_empty()
        assert not t.with_data(d + 1).is_empty()

    def test_window_geotransform(self, rng):
        t = dem_tile(rng)
        w = t.window(8, 4, 16, 16)
        # window's upper-left world coord == parent's pixel (8,4) coord
        x, y = t.gt.to_world(8, 4)
        assert w.gt.x0 == pytest.approx(x)
        assert w.gt.y0 == pytest.approx(y)
        assert np.array_equal(np.asarray(w.data),
                              np.asarray(t.data)[:, 4:20, 8:24])

    def test_band_out_of_range(self, rng):
        with pytest.raises(IndexError):
            dem_tile(rng).band(5)


class TestOps:
    def test_clip_to_cell_masks_outside(self, rng, ctx):
        t = dem_tile(rng)
        grid = ctx.index_system
        cells = grid.candidate_cells(np.array([0, 0, 16, 16]), 2)
        ct = rops.clip_to_cell(t, int(cells[5]), grid)
        assert ct.cell_id == int(cells[5])
        # all valid pixels' centers must fall inside the cell bbox
        xs, ys = ct.pixel_centers()
        m = ct.valid_mask()[0]
        verts, counts = grid.cell_boundary(cells[5:6])
        ring = verts[0, :counts[0]]
        assert xs[m].min() >= ring[:, 0].min() - 1e-9
        assert xs[m].max() <= ring[:, 0].max() + 1e-9
        assert ys[m].min() >= ring[:, 1].min() - 1e-9
        assert ys[m].max() <= ring[:, 1].max() + 1e-9

    def test_tessellate_partitions_pixels(self, rng, ctx):
        """Every pixel appears in exactly one cell tile (grid-aligned
        raster ⇒ clean partition)."""
        t = dem_tile(rng, 64, 64)
        tiles = rops.tessellate_raster(t, 2, ctx.index_system)
        total = sum(int(x.valid_mask().sum()) for x in tiles)
        assert total == 64 * 64

    def test_merge_and_combine(self, rng):
        t = dem_tile(rng, 32, 32)
        left = t.window(0, 0, 16, 32)
        right = t.window(16, 0, 16, 32)
        m = rops.merge([left, right])
        np.testing.assert_allclose(np.asarray(m.data),
                                   np.asarray(t.data, np.float64))
        c = rops.combine([t, t.with_data(np.asarray(t.data) + 10)], "avg")
        np.testing.assert_allclose(np.asarray(c.data),
                                   np.asarray(t.data, np.float64) + 5)

    def test_combine_reducers(self, rng):
        t = dem_tile(rng, 8, 8)
        t2 = t.with_data(np.asarray(t.data) + 10)
        assert np.allclose(np.asarray(rops.combine([t, t2], "min").data),
                           np.asarray(t.data, np.float64))
        assert np.allclose(np.asarray(rops.combine([t, t2], "max").data),
                           np.asarray(t.data, np.float64) + 10)
        assert np.allclose(np.asarray(rops.combine([t, t2],
                                                   "count").data), 2)

    def test_ndvi_oracle(self, rng):
        d = rng.uniform(1, 100, (2, 16, 16)).astype(np.float32)
        t = RasterTile(d, GeoTransform(0, 1, 0, 16, 0, -1))
        out = rops.ndvi(t, 0, 1)
        red, nir = d[0].astype(np.float64), d[1].astype(np.float64)
        np.testing.assert_allclose(np.asarray(out.data[0]),
                                   (nir - red) / (nir + red), rtol=1e-12)

    def test_convolve_box_oracle(self, rng):
        d = rng.uniform(0, 10, (1, 12, 12)).astype(np.float64)
        t = RasterTile(d, GeoTransform(0, 1, 0, 12, 0, -1))
        k = np.ones((3, 3))
        out = np.asarray(rops.convolve(t, k).data[0])
        # interior pixel oracle
        for (r, c) in [(5, 5), (3, 8)]:
            assert out[r, c] == pytest.approx(
                d[0, r - 1:r + 2, c - 1:c + 2].sum())

    def test_filter_median(self, rng):
        d = rng.uniform(0, 10, (1, 9, 9))
        t = RasterTile(d, GeoTransform(0, 1, 0, 9, 0, -1))
        out = np.asarray(rops.filter_tile(t, 3, "median").data[0])
        assert out[4, 4] == pytest.approx(np.median(d[0, 3:6, 3:6]))

    def test_subdivide_respects_bound(self, rng):
        t = dem_tile(rng, 128, 128)
        parts = rops.subdivide(t, 0.01)       # 10 KB bound
        assert all(p.memsize() <= 0.01 * (1 << 20) for p in parts)
        assert sum(p.width * p.height for p in parts) == 128 * 128

    def test_retile_covers(self, rng):
        t = dem_tile(rng, 50, 70)
        parts = rops.retile(t, 32, 32)
        assert sum(p.width * p.height for p in parts) == 50 * 70


class TestRstSurface:
    def test_accessors(self, rng, ctx):
        t = dem_tile(rng, 32, 48, bands=2)
        assert ctx.rst_height([t])[0] == 32
        assert ctx.rst_width([t])[0] == 48
        assert ctx.rst_numbands([t])[0] == 2
        assert ctx.rst_scalex([t])[0] == pytest.approx(16.0 / 48)
        assert ctx.rst_srid([t])[0] == 4326
        assert ctx.rst_pixelcount([t])[0] == 2 * 32 * 48
        assert not ctx.rst_isempty([t])[0]

    def test_write_read_surface(self, rng, ctx):
        t = dem_tile(rng, 16, 16)
        blobs = ctx.rst_write([t])
        assert ctx.rst_tryopen(blobs) == [True]
        assert ctx.rst_tryopen([b"junk"]) == [False]
        back = ctx.rst_fromcontent(blobs)[0]
        np.testing.assert_array_equal(np.asarray(back.data),
                                      np.asarray(t.data))

    def test_frombands_separatebands(self, rng, ctx):
        t = dem_tile(rng, 8, 8, bands=3)
        bands = ctx.rst_separatebands([t])
        assert len(bands) == 3
        back = ctx.rst_frombands(bands)
        np.testing.assert_array_equal(np.asarray(back.data),
                                      np.asarray(t.data))

    def test_rastertogrid_oracle(self, rng, ctx):
        t = dem_tile(rng, 64, 64)
        got = ctx.rst_rastertogridavg([t], 2)[0]
        xs, ys = t.pixel_centers()
        cells = ctx.index_system.point_to_cell(
            np.stack([xs.ravel(), ys.ravel()], -1), 2)
        vals = np.asarray(t.data[0], np.float64).ravel()
        for c in np.unique(cells):
            assert got[int(c)] == pytest.approx(vals[cells == c].mean(),
                                                rel=1e-12)

    def test_world_coord_surface(self, rng, ctx):
        t = dem_tile(rng)
        xy = ctx.rst_rastertoworldcoord([t], [0], [0])
        assert xy[0, 0] == pytest.approx(0.0)
        assert xy[0, 1] == pytest.approx(16.0)
        cr = ctx.rst_worldtorastercoord([t], [8.0], [8.0])
        assert cr[0, 0] == 32 and cr[0, 1] == 32


class TestRasterToGrid:
    def test_pipeline_matches_oracle(self, rng, ctx):
        """BASELINE config 5 in miniature: synthetic DEM → grid measures,
        vs direct per-cell pixel binning."""
        t = dem_tile(rng, 64, 64)
        got = raster_to_grid([t], 2, ctx.index_system, "avg")
        xs, ys = t.pixel_centers()
        cells = ctx.index_system.point_to_cell(
            np.stack([xs.ravel(), ys.ravel()], -1), 2)
        vals = np.asarray(t.data[0], np.float64).ravel()
        assert set(got) == set(int(c) for c in np.unique(cells))
        for c in np.unique(cells):
            assert got[int(c)] == pytest.approx(vals[cells == c].mean(),
                                                rel=1e-9)

    def test_pipeline_overlapping_tiles(self, rng, ctx):
        """Two overlapping tiles: per-cell combine averages them."""
        t = dem_tile(rng, 32, 32)
        t2 = t.with_data(np.asarray(t.data) + 100)
        got = raster_to_grid([t, t2], 2, ctx.index_system, "avg")
        solo = raster_to_grid([t], 2, ctx.index_system, "avg")
        for c, v in solo.items():
            # t2's +100 rounds in its float32 storage before combining
            assert got[c] == pytest.approx(v + 50, rel=1e-5)

    def test_subdivision_invariance(self, rng, ctx):
        """raster_to_grid over subdivided halves == over the whole
        raster, even when pixel centers align exactly with cell
        boundaries (the windowed-frame ulp tie regression)."""
        dem = rng.uniform(0, 500, (1, 96, 96)).astype(np.float32)
        t = RasterTile(dem, GeoTransform(0.0, 16 / 96, 0, 16.0, 0,
                                         -16 / 96), nodata=-1.0)
        whole = raster_to_grid([t], 2, ctx.index_system, "avg")
        halves = rops.subdivide(t, 0.02)
        assert len(halves) > 1
        split = raster_to_grid(halves, 2, ctx.index_system, "avg")
        assert set(whole) == set(split)
        for c, v in whole.items():
            assert split[c] == pytest.approx(v, rel=1e-12)

    def test_kring_interpolation(self, rng, ctx):
        # 64×64 px over a 64×64-cell grid: every cell carries a value,
        # so each 1-ring has 9 valued members and smoothing contracts
        t = dem_tile(rng, 64, 64)
        plain = raster_to_grid([t], 2, ctx.index_system, "avg")
        smooth = raster_to_grid([t], 2, ctx.index_system, "avg",
                                kring_interpolate=1)
        assert set(plain) == set(smooth)
        # smoothing shrinks the value spread
        assert np.std(list(smooth.values())) < np.std(list(plain.values()))
