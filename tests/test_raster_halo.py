"""Sharded-raster halo exchange vs the single-device stencil.

The slab-sharded convolve (parallel/raster_halo.py: shard_map +
ppermute halo rows) must equal rops.convolve exactly — seams between
device slabs are where a missing/misdirected halo shows up.
"""

import numpy as np
import pytest

from mosaic_tpu.core.raster.rops import convolve
from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:8]), axis_names=("data",))


def _tile(h=64, w=40, bands=2, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.normal(0, 10, (bands, h, w))
    gt = GeoTransform(-74.0, 0.001, 0.0, 40.9, 0.0, -0.001)
    return RasterTile(data, gt, srid=4326)


@pytest.mark.parametrize("ksize", [3, 5])
def test_matches_single_device(mesh, ksize):
    from mosaic_tpu.parallel.raster_halo import sharded_convolve
    t = _tile()
    rng = np.random.default_rng(ksize)
    k = rng.normal(0, 1, (ksize, ksize))
    want = convolve(t, k)
    got = sharded_convolve(t, k, mesh)
    # f32 conv reduction order differs between the full-height and
    # widened-slab shapes -> ulp-level differences, not bit equality
    np.testing.assert_allclose(got.data, want.data, rtol=2e-6,
                               atol=1e-4)


def test_nodata_respected(mesh):
    from mosaic_tpu.parallel.raster_halo import sharded_convolve
    t = _tile(seed=3)
    d = np.asarray(t.data).copy()
    d[0, 10:20, 5:15] = -9999.0
    t2 = RasterTile(d, t.gt, nodata=-9999.0, srid=4326)
    k = np.ones((3, 3)) / 9.0
    want = convolve(t2, k)
    got = sharded_convolve(t2, k, mesh)
    np.testing.assert_allclose(got.data, want.data, rtol=2e-6,
                               atol=1e-4)


def test_guards(mesh):
    from mosaic_tpu.parallel.raster_halo import sharded_convolve
    t = _tile(h=63)     # not divisible by 8
    with pytest.raises(ValueError, match="divide"):
        sharded_convolve(t, np.ones((3, 3)), mesh)
    with pytest.raises(ValueError, match="odd"):
        sharded_convolve(_tile(), np.ones((2, 2)), mesh)
