"""Real-data fixtures: actual NYC taxi zones x actual yellow-cab trips.

The zones are the reference's own Quickstart fixture
(src/test/resources/NYC_Taxi_Zones.geojson — NYC open data, 35
Manhattan-area MultiPolygons) and the trips a sample of its
nyctaxi_yellow_trips.csv.  Until round 4 every test and bench input was
synthetic (VERDICT round-3 missing #6); these pin the flagship join on
real geometry: self-intersection-free ingest, tessellation coverage,
and exact PIP parity.
"""

import csv
import json
import os

import numpy as np
import pytest

from mosaic_tpu.core.geometry.geojson import read_geojson
from mosaic_tpu.core.index.factory import get_index_system

DATA = os.path.join(os.path.dirname(__file__), "data")


@pytest.fixture(scope="module")
def zones():
    feats = []
    with open(os.path.join(DATA, "nyc_taxi_zones.geojson")) as f:
        for line in f:
            line = line.strip()
            if line:
                feats.append(json.loads(line))
    geoms = read_geojson([json.dumps(fe["geometry"]) for fe in feats])
    names = [fe["properties"]["zone"] for fe in feats]
    return geoms, names


@pytest.fixture(scope="module")
def trips():
    with open(os.path.join(DATA, "nyc_taxi_trips_sample.csv")) as f:
        rows = list(csv.DictReader(f))
    return np.array([[float(r["pickup_longitude"]),
                      float(r["pickup_latitude"])] for r in rows])


def test_ingest_real_zones(zones):
    geoms, names = zones
    assert len(geoms) == 35
    assert "Bloomingdale" in names
    from mosaic_tpu.functions.context import MosaicContext
    areas = MosaicContext.build("H3").st_area(geoms)
    assert np.all(areas > 0)
    # direct shoelace of the first feature's ring (the file's
    # shape_area property was computed upstream in another CRS and
    # does not match the geometry's planar degree area)
    assert areas[0] == pytest.approx(4.193691052023496e-05, rel=1e-12)


def test_tessellate_real_zones(zones):
    geoms, _ = zones
    grid = get_index_system("H3")
    from mosaic_tpu.core.tessellate import tessellate
    chips = tessellate(geoms, 9, grid, keep_core_geom=True)
    assert len(chips) > 500
    assert chips.is_core.sum() > 0
    # chip areas sum back to the zone areas (chips partition each zone)
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    zone_area = mc.st_area(geoms)
    chip_area = mc.st_area(chips.geoms)
    got = np.zeros(len(geoms))
    np.add.at(got, chips.geom_id, chip_area)
    # real 250-vertex coastline rings accumulate ~1e-8 relative
    # f64 clip rounding; exactness for the JOIN is row parity (below),
    # not bit-identical areas
    np.testing.assert_allclose(got, zone_area, rtol=1e-6)


def test_real_pip_join_exact(zones, trips):
    import jax
    geoms, names = zones
    grid = get_index_system("H3")
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn, localize,
                                              make_pip_join_fn,
                                              pip_host_truth)
    idx = build_pip_index(geoms, 9, grid)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    zone, unc = fn(localize(idx, trips))
    zone = np.asarray(zone).copy()
    zone = host_recheck_fn(idx, geoms)(trips, zone,
                                       np.asarray(unc))
    truth = pip_host_truth(trips, geoms)
    assert np.array_equal(zone, truth)
    # the sample has real matches (Manhattan pickups in these zones)
    assert (truth >= 0).sum() > 10


def test_real_quickstart_sql(zones, trips):
    from mosaic_tpu.functions.context import MosaicContext
    from mosaic_tpu.sql import SQLSession
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.parallel.pip_join import pip_host_truth
    geoms, names = zones
    mc = MosaicContext.build("H3")
    s = SQLSession(mc)
    b = GeometryBuilder()
    for p in trips:
        b.add_point(p)
    s.create_table("trips", {"geom": b.finish(),
                             "tid": np.arange(len(trips))})
    s.create_table("zones", {"zgeom": geoms,
                             "zid": np.arange(len(geoms),
                                              dtype=np.int64)})
    s.create_table("pts", s.sql(
        "SELECT tid, grid_pointascellid(geom, 9) AS cell, geom "
        "FROM trips").to_dict())
    s.create_table("chips", s.sql(
        "SELECT zid, grid_tessellateexplode(zgeom, 9) FROM zones"
    ).to_dict())
    out = s.sql("SELECT tid, zid FROM pts JOIN chips "
                "ON pts.cell = chips.index_id "
                "WHERE is_core OR st_contains(wkb, geom)")
    truth = pip_host_truth(trips, geoms)
    got = np.full(len(trips), -1, np.int64)
    got[np.asarray(out.columns["tid"])] = \
        np.asarray(out.columns["zid"])
    assert np.array_equal(got, truth)


def test_epsg_bounds_table():
    """The per-EPSG bounds resource resolves codes far beyond the
    analytic handful (reference: CRSBoundsProvider resource list)."""
    from mosaic_tpu.core.geometry.crs import crs_bounds
    # a state-plane CRS only the table knows
    b = crs_bounds(2853, reprojected=False)
    assert b[0] == pytest.approx(-80.05) and b[3] == pytest.approx(39.45)
    bp = crs_bounds(2853, reprojected=True)
    assert bp[0] == pytest.approx(3363434.3107)
    # analytic CRSs still take the exact path
    assert crs_bounds(4326, reprojected=False) == (-180.0, -90.0,
                                                   180.0, 90.0)
    with pytest.raises(ValueError, match="no bounds"):
        crs_bounds(999999)
    # st_hasvalidcoordinates through the public surface
    from mosaic_tpu.functions.context import MosaicContext
    mc = MosaicContext.build("H3")
    import mosaic_tpu as mos
    g = mos.read_wkt(["POINT (-78 38.5)"])
    assert mc.st_hasvalidcoordinates(g, "EPSG:2853", "bounds").all()
