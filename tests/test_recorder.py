"""Flight recorder, trace contexts, and OpenMetrics export.

Covers the PR-4 observability surface: ring bounds, dump-on-error
bundle shape (failing span chain + ErrorRecord + metrics snapshot),
the slow-query trigger, trace-id propagation across threads, recorder
events from an armed fault plan, retry events, and an OpenMetrics
round-trip through a live scrape of ``serve_metrics``.
"""

import json
import struct
import threading
import urllib.request

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu import config as _config
from mosaic_tpu.obs import (chrome_trace_events, current_trace_id,
                            install_jax_listeners, metrics, new_trace,
                            recorder, root_trace, serve_metrics,
                            to_openmetrics, tracer)
from mosaic_tpu.resilience import faults
from mosaic_tpu.resilience.ingest import CodecError, ErrorSink, decode_guard
from mosaic_tpu.resilience.retry import RetryPolicy


@pytest.fixture
def clean_obs():
    """Fresh tracer + recorder + registry for one test."""
    recorder.reset()
    recorder.enable()
    tracer.reset()
    tracer.enable()
    yield
    tracer.disable()
    tracer.reset()
    recorder.reset()


@pytest.fixture
def clean_config():
    """Restore the session-default config after the test."""
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


@pytest.fixture
def session():
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    s = mos.SQLSession(ctx)
    s.create_table("pts", {"x": np.arange(100.0),
                           "y": np.arange(100.0) / 10.0})
    return s


# ------------------------------------------------------------- ring

def test_ring_is_bounded(clean_obs):
    recorder.reset(capacity=32)
    try:
        for i in range(100):
            recorder.record("tick", i=i)
        evs = recorder.events("tick")
        assert len(evs) == 32
        # oldest events fell off the front, newest survived
        assert evs[0]["i"] == 68 and evs[-1]["i"] == 99
        # seq stays monotonically increasing across the wrap
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)
    finally:
        recorder.reset(capacity=4096)


def test_disabled_recorder_records_nothing(clean_obs):
    recorder.disable()
    recorder.record("tick")
    assert recorder.events() == []
    recorder.enable()
    recorder.record("tick")
    assert len(recorder.events("tick")) == 1


# ------------------------------------------------- dump-on-error

def test_dump_on_error_bundle_shape(clean_obs, clean_config, tmp_path,
                                    monkeypatch):
    """A forced codec error dumps a bundle holding the failing span
    chain, the located error, and a metrics snapshot."""
    monkeypatch.setenv("MOSAIC_TPU_DUMP_DIR", str(tmp_path))
    metrics.count("io/records_dropped")      # something to snapshot
    with pytest.raises(CodecError):
        with recorder.dump_on_error(reason="test_error"):
            with new_trace("ingest:broken") as ctx:
                with tracer.span("read_file"):
                    with tracer.span("decode_strip"):
                        with decode_guard(path="f.bin",
                                          feature="strip 3", offset=77):
                            raise struct.error("unpack requires more")
    dumps = list(tmp_path.glob("*_test_error.json"))
    assert len(dumps) == 1
    b = json.loads(dumps[0].read_text())
    assert b["reason"] == "test_error"
    assert b["error"].startswith("CodecError")
    # metrics snapshot + resolved config + jax platform info
    assert b["metrics"]["counters"]["io/records_dropped"] == 1
    assert b["config"]["index_system"]
    assert "jax" in b
    # the located codec error event, attributed to the trace
    (ce,) = [e for e in b["events"] if e["kind"] == "codec_error"]
    assert ce["feature"] == "strip 3" and ce["offset"] == 77
    assert ce["trace"] == ctx.trace_id
    # the failing span chain: both spans errored, child links parent
    spans = {e["name"]: e for e in b["events"] if e["kind"] == "span"}
    child = spans["read_file/decode_strip"]
    parent = spans["read_file"]
    assert child["parent"] == parent["span"]
    assert child["error"].startswith("CodecError")
    assert parent["error"].startswith("CodecError")
    assert child["trace"] == parent["trace"] == ctx.trace_id


def test_error_sink_drop_lands_in_recorder(clean_obs):
    sink = ErrorSink("skip", driver="grib", path="g.grib")
    with pytest.raises(CodecError):
        # decode_guard locates, sink.handle absorbs
        with decode_guard(path="g.grib", feature="message 2", offset=9):
            raise IndexError("short buffer")
    try:
        with decode_guard(path="g.grib", feature="message 2", offset=9):
            raise IndexError("short buffer")
    except CodecError as e:
        sink.handle(e)
    (ev,) = recorder.events("codec_record_dropped")
    assert ev["driver"] == "grib" and ev["feature"] == "message 2"
    assert sink.dropped() == 1


# ------------------------------------------------- slow-query dump

def test_slow_query_triggers_dump(clean_obs, clean_config, session,
                                  tmp_path, monkeypatch):
    monkeypatch.setenv("MOSAIC_TPU_DUMP_DIR", str(tmp_path))
    cfg = _config.apply_conf(_config.default_config(),
                             _config.MOSAIC_OBS_SLOW_QUERY_MS, "0.0001")
    _config.set_default_config(cfg)
    session.sql("SELECT x FROM pts WHERE y > 1.0")
    dumps = list(tmp_path.glob("*_slow_query.json"))
    assert len(dumps) == 1
    b = json.loads(dumps[0].read_text())
    (sq,) = [e for e in b["events"] if e["kind"] == "slow_query"]
    assert sq["ms"] > sq["threshold_ms"]
    assert sq["query"].startswith("SELECT x FROM pts")
    # the slow query's trace id points at its span tree in the bundle
    q_spans = [e for e in b["events"]
               if e["kind"] == "span" and e.get("trace") == sq["trace"]]
    assert any(e["name"] == "sql/query" for e in q_spans)


def test_no_dump_when_threshold_unset(clean_obs, clean_config, session,
                                      tmp_path, monkeypatch):
    monkeypatch.setenv("MOSAIC_TPU_DUMP_DIR", str(tmp_path))
    session.sql("SELECT x FROM pts")
    assert list(tmp_path.glob("*.json")) == []


def test_slow_query_conf_validates(clean_config):
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(_config.default_config(),
                           _config.MOSAIC_OBS_SLOW_QUERY_MS, "-5")
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(_config.default_config(),
                           _config.MOSAIC_OBS_SLOW_QUERY_MS, "soon")


def test_config_mutation_is_recorded(clean_obs, clean_config):
    _config.apply_conf(_config.default_config(),
                       _config.MOSAIC_IO_ON_ERROR, "skip")
    (ev,) = recorder.events("config")
    assert ev["key"] == _config.MOSAIC_IO_ON_ERROR
    assert ev["value"] == "skip"


# --------------------------------------------------- trace contexts

def test_trace_id_propagates_across_threads(clean_obs):
    seen = {}

    def worker():
        seen["trace"] = current_trace_id()
        with tracer.span("worker_span"):
            pass

    with new_trace("parent") as ctx:
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["trace"] == ctx.trace_id
    spans = tracer.report()["traces"][ctx.trace_id]["spans"]
    assert [s["name"] for s in spans] == ["worker_span"]


def test_thread_without_trace_is_untouched(clean_obs):
    seen = {}
    t = threading.Thread(
        target=lambda: seen.update(trace=current_trace_id()))
    t.start()
    t.join()
    assert seen["trace"] is None


def test_root_trace_joins_active_trace(clean_obs):
    with new_trace("outer") as outer:
        with root_trace("inner") as inner:
            assert inner.trace_id == outer.trace_id
    with root_trace("standalone") as alone:
        assert alone.trace_id != outer.trace_id
        assert alone.name == "standalone"


def test_interleaved_queries_get_distinct_trace_trees(clean_obs,
                                                      session):
    """The acceptance shape: two interleaved sql() calls -> two trace
    ids, each with a correctly-parented span tree, in report() and in
    the Chrome-trace export."""
    barrier = threading.Barrier(2, timeout=30)
    results = {}

    def run(tag, query):
        barrier.wait()               # both queries in flight together
        results[tag] = session.sql(query)

    t1 = threading.Thread(target=run,
                          args=("a", "SELECT x FROM pts WHERE y > 1.0"))
    t2 = threading.Thread(target=run,
                          args=("b", "SELECT y FROM pts ORDER BY y DESC"))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert len(results["a"]) == 89 and len(results["b"]) == 100

    traces = tracer.report()["traces"]
    sql_traces = {tid: t for tid, t in traces.items()
                  if t["name"].startswith("sql:")}
    assert len(sql_traces) == 2
    for tid, t in sql_traces.items():
        by_name = {s["name"]: s for s in t["spans"]}
        root = by_name["sql/query"]
        assert root["parent_id"] is None
        # every operator stage is a direct child of the query root
        stages = [s for n, s in by_name.items()
                  if n.startswith("sql/query/")]
        assert stages, t
        assert all(s["parent_id"] == root["span_id"] for s in stages)
    # span ids never collide across the two traces
    ids_a, ids_b = [set(s["span_id"] for s in t["spans"])
                    for t in sql_traces.values()]
    assert not (ids_a & ids_b)

    # Chrome-trace export: one lane per query, labelled by trace id
    doc = chrome_trace_events()
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"
          and e["args"].get("trace_id") in sql_traces]
    assert {e["args"]["trace_id"] for e in xs} == set(sql_traces)
    lane_of = {}
    for e in xs:
        lane_of.setdefault(e["args"]["trace_id"], set()).add(e["tid"])
    # the two queries never share a lane
    a_lanes, b_lanes = lane_of.values()
    assert not (a_lanes & b_lanes)
    names = {m["args"]["name"] for m in doc["traceEvents"]
             if m["ph"] == "M" and m["name"] == "thread_name"}
    for tid in sql_traces:
        assert any(tid in n for n in names)


def test_explain_analyze_rows_are_trace_spans(clean_obs, session):
    out = session.sql("EXPLAIN ANALYZE SELECT x FROM pts WHERE y > 5.0")
    ops = list(out.columns["operator"])
    traces = tracer.report()["traces"]
    (trace,) = [t for t in traces.values()
                if t["name"].startswith("sql:EXPLAIN")]
    span_names = {s["name"] for s in trace["spans"]}
    for op in ops:
        assert f"sql/query/{op}" in span_names


# ------------------------------------------------ resilience events

def test_fault_plan_firings_land_in_recorder(clean_obs, fault_plan):
    plan = fault_plan("seed=7;site=recorder.test,fails=2")
    with pytest.raises(OSError):
        faults.maybe_fail("recorder.test")
    with pytest.raises(OSError):
        faults.maybe_fail("recorder.test")
    faults.maybe_fail("recorder.test")       # third call: clean
    evs = recorder.events("fault_injected")
    assert [(e["site"], e["call"]) for e in evs] == \
        [("recorder.test", 0), ("recorder.test", 1)]
    assert all(e["seed"] == 7 for e in evs)
    assert len(plan.injected) == 2


def test_retry_attempts_land_in_recorder(clean_obs):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(f"blip {calls['n']}")
        return "ok"

    policy = RetryPolicy(name="rec.test", max_attempts=4,
                         base_delay_s=0.0, jitter=0.0)
    assert policy.call(flaky, sleep=lambda _s: None) == "ok"
    attempts = recorder.events("retry")
    assert [e["attempt"] for e in attempts] == [0, 1]
    assert all(e["policy"] == "rec.test" and "blip" in e["error"]
               for e in attempts)
    (rec_ev,) = recorder.events("retry_recovered")
    assert rec_ev["attempts"] == 3

    with pytest.raises(OSError):
        policy.call(lambda: (_ for _ in ()).throw(OSError("dead")),
                    sleep=lambda _s: None)
    (gu,) = recorder.events("retry_giveup")
    assert gu["policy"] == "rec.test" and "dead" in gu["error"]


def test_jax_compile_recorded_with_metrics_off(clean_obs):
    """The recorder sees backend compiles even when the registry is
    disabled — crash bundles must show pre-crash compile activity."""
    import jax
    import jax.numpy as jnp
    install_jax_listeners()
    tracer.disable()                 # registry off too
    assert not metrics.enabled
    jax.jit(lambda v: v * 3 + 1)(jnp.arange(7))
    assert recorder.events("jax_compile")
    tracer.enable()


# ------------------------------------------------------ openmetrics

def test_to_openmetrics_exposition(clean_obs):
    metrics.count("io/records_dropped", 3)
    metrics.gauge("shard/skew/pip_join", 1.25)
    for v in (0.001, 0.002, 0.004):
        metrics.observe("sql/scan_s", v)
    txt = to_openmetrics()
    assert txt.endswith("# EOF\n")
    assert "# TYPE mosaic_io_records_dropped_total counter" in txt
    assert "mosaic_io_records_dropped_total 3" in txt
    assert "mosaic_shard_skew_pip_join 1.25" in txt
    assert "# TYPE mosaic_sql_scan_s histogram" in txt
    assert 'mosaic_sql_scan_s_bucket{le="+Inf"} 3' in txt
    assert "mosaic_sql_scan_s_count 3" in txt
    # cumulative buckets are nondecreasing and end at count
    cums = [int(l.rsplit(" ", 1)[1]) for l in txt.splitlines()
            if l.startswith("mosaic_sql_scan_s_bucket")]
    assert cums == sorted(cums) and cums[-1] == 3
    assert txt == metrics.to_openmetrics()


def test_openmetrics_roundtrip_through_scrape(clean_obs):
    metrics.count("jax/recompiles", 2)
    metrics.observe("sql/project_s", 0.01)
    server = serve_metrics(port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            body = r.read().decode("utf-8")
        assert body == to_openmetrics()
        assert "mosaic_jax_recompiles_total 2" in body
        assert "mosaic_sql_project_s_sum 0.01" in body
        # scrapes see live values: bump and scrape again
        metrics.count("jax/recompiles", 1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert "mosaic_jax_recompiles_total 3" in r.read().decode()
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=10)
    finally:
        server.shutdown()
        server.server_close()


# ------------------------------------------------------ bundle misc

def test_bundle_carries_platform_info(clean_obs):
    b = recorder.bundle(reason="t")
    assert b["jax"]["imported"] is True
    assert b["jax"]["device_count"] == 8      # conftest's virtual mesh
    assert b["config"]["io_on_error"] in ("raise", "skip", "null")


def test_dump_event_is_appended(clean_obs, tmp_path):
    p = recorder.dump(path=str(tmp_path / "x.json"), reason="manual")
    assert p == str(tmp_path / "x.json")
    (ev,) = recorder.events("dump")
    assert ev["path"] == p and ev["reason"] == "manual"
