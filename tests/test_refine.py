"""Adaptive PIP refinement: refined-vs-flat bit-parity, compile
accounting, planner pins, chaos, and the observability plumbing.

The refined join (parallel/pip_join.make_refined_pip_join) is a
strategy transform, never an answer transform: every test here asserts
results bit-for-bit against the flat single-level path and/or the
float64 host oracle (pip_host_truth).  The clean-index parity theorem
lives in pip_join._chips_clean's docstring; these tests are its
empirical side.
"""

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.core.geometry.array import GeometryBuilder
from mosaic_tpu.core.index.h3.system import H3IndexSystem
from mosaic_tpu.obs import inflight, metrics
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                          make_refined_pip_join,
                                          make_streamed_pip_join,
                                          pip_host_truth)
from mosaic_tpu.perf.jit_cache import kernel_cache


@pytest.fixture()
def conf():
    """Snapshot/restore the process config around each test."""
    prev = _config.default_config()
    yield
    _config.set_default_config(prev)


def _set(key, val):
    _config.set_default_config(_config.apply_conf(
        _config.default_config(), key, val))


def _cluster_polys(n=40, radius=0.004, spread=0.1, seed=0):
    """A tight cluster of small polygons sharing coarse grid cells —
    high per-cell chip duplication, the refinement target workload."""
    rng = np.random.default_rng(seed)
    b = GeometryBuilder()
    for cx, cy in rng.uniform(-spread, spread, size=(n, 2)):
        ang = np.linspace(0.0, 2.0 * np.pi, 8)[:-1]
        b.add_polygon(np.stack([cx + radius * np.cos(ang),
                                cy + radius * np.sin(ang)], 1), [])
    return b.finish()


def _points(kind, n, seed):
    rng = np.random.default_rng(seed)
    if kind == "uniform":
        return rng.uniform(-0.15, 0.15, size=(n, 2))
    if kind == "skewed":
        return np.concatenate([
            rng.uniform(-0.12, 0.12, size=(n * 3 // 4, 2)),
            rng.uniform(-2.0, 2.0, size=(n - n * 3 // 4, 2))])
    if kind == "clustered":
        c = rng.uniform(-0.1, 0.1, size=(8, 2))
        return (c[rng.integers(0, 8, n)]
                + rng.normal(0.0, 0.01, size=(n, 2)))
    if kind == "empty_cells":
        # every point far outside the polygon cluster: the probe sees
        # zero candidate pairs, the dense set is empty
        return rng.uniform(50.0, 60.0, size=(n, 2))
    raise AssertionError(kind)


GRID = H3IndexSystem()
RES = 5


def _flat_reference(polys, pts):
    idx = build_pip_index(polys, RES, GRID, dense="never")
    flat = make_streamed_pip_join(idx, GRID, polys=polys, chunk=4096)
    z, _ = flat(pts)
    return np.asarray(z)


@pytest.mark.parametrize("kind", ["uniform", "skewed", "clustered",
                                  "empty_cells"])
def test_refined_vs_flat_bit_parity(conf, kind):
    """Fuzz the refined path against the flat path AND the float64
    host oracle across point distributions — including the empty-dense
    case where the probe finds nothing to refine."""
    _set("mosaic.planner.force.refine", "refined")
    _set("mosaic.join.refine.dup.threshold", "2")
    polys = _cluster_polys(seed=3)
    pts = _points(kind, 12_000, seed=11)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    z_ref, _ = run(pts)
    z_flat = _flat_reference(polys, pts)
    assert np.array_equal(np.asarray(z_ref), z_flat)
    assert np.array_equal(np.asarray(z_ref), pip_host_truth(pts, polys))
    assert run.last_decision is not None
    assert run.stats["strategy"] in ("refined", "flat")
    if kind == "skewed":
        assert run.stats["strategy"] == "refined"
        assert run.stats["levels"] == [RES, RES + 1]
        assert run.stats["refined_points"] > 0


def test_one_compile_per_level_and_bucket(conf):
    """A warm refined process compiles nothing new: kernels are cached
    per (level, pow2 bucket), so repeat calls — and a second join over
    the same shapes — reuse every compiled executable."""
    _set("mosaic.planner.force.refine", "refined")
    _set("mosaic.join.refine.dup.threshold", "2")
    polys = _cluster_polys(seed=5)
    pts = _points("skewed", 10_000, seed=21)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    z0, _ = run(pts)                # cold: probe + compiles
    s0 = kernel_cache.stats()
    for _ in range(3):
        z1, _ = run(pts)
        assert np.array_equal(np.asarray(z0), np.asarray(z1))
    s1 = kernel_cache.stats()
    assert s1["misses"] == s0["misses"], \
        "warm refined reps must not compile"
    assert s1["hits"] > s0["hits"]


def test_refine_disabled_kill_switch(conf):
    """mosaic.join.refine.enabled=false forces the flat path — it
    beats any pin — and the answer is unchanged."""
    _set("mosaic.join.refine.enabled", "false")
    _set("mosaic.planner.force.refine", "refined")   # loses to the switch
    polys = _cluster_polys(seed=7)
    pts = _points("skewed", 8_000, seed=31)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    z, _ = run(pts)
    d = run.last_decision
    assert d.strategy == "flat" and d.forced
    assert run.stats["strategy"] == "flat"
    assert np.array_equal(np.asarray(z), _flat_reference(polys, pts))


def test_refine_forced_pin_parity(conf):
    """Pinning refined vs flat through mosaic.planner.force.refine
    yields bit-identical answers (the planner only picks speed)."""
    _set("mosaic.join.refine.dup.threshold", "2")
    polys = _cluster_polys(seed=9)
    pts = _points("skewed", 8_000, seed=41)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    _set("mosaic.planner.force.refine", "refined")
    z_ref, _ = run(pts)
    assert run.last_decision.forced
    assert run.stats["strategy"] == "refined"
    _set("mosaic.planner.force.refine", "flat")
    z_flat, _ = run(pts)
    assert run.last_decision.forced
    assert run.stats["strategy"] == "flat"
    assert np.array_equal(np.asarray(z_ref), np.asarray(z_flat))


def test_refine_chaos_bailout(conf, fault_plan):
    """An injected fault at site=join.refine mid-refined-run falls
    back to the flat path transparently: correct answer, a
    refine_bailout flight-recorder event, and the bailout counter."""
    _set("mosaic.planner.force.refine", "refined")
    _set("mosaic.join.refine.dup.threshold", "2")
    polys = _cluster_polys(seed=13)
    pts = _points("skewed", 8_000, seed=51)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    recorder.reset()
    recorder.enable()
    metrics.enable()
    c0 = metrics.counter_value("pip_join/refine_bailouts")
    try:
        fault_plan("seed=17;site=join.refine,fails=1")
        z, _ = run(pts)
    finally:
        recorder.disable()
    assert np.array_equal(np.asarray(z), pip_host_truth(pts, polys))
    assert run.stats["strategy"] == "flat"
    evs = recorder.events("refine_bailout")
    assert len(evs) == 1 and evs[0]["error"].startswith("Injected")
    assert metrics.counter_value("pip_join/refine_bailouts") == c0 + 1


def test_refine_ticket_cost_and_strategy(conf):
    """A refined join under a registered query ticket lands its cell
    counters in the inflight cost vector and its decision label in the
    strategies map (the history/mosaicstat strategies feed)."""
    from mosaic_tpu.obs.context import root_trace
    _set("mosaic.planner.force.refine", "refined")
    _set("mosaic.join.refine.dup.threshold", "2")
    polys = _cluster_polys(seed=15)
    pts = _points("skewed", 8_000, seed=61)
    run = make_refined_pip_join(polys, GRID, RES, chunk=4096)
    inflight.enabled = True
    with root_trace("q"):
        t = inflight.register("test refine", principal="t")
        try:
            run(pts)
            cost = t.cost()
            assert cost["cells_refined"] > 0
            assert cost["cells_flat"] >= 0
            assert "refine" in t.strategies
            assert t.strategies["refine"].startswith("refined")
            assert t.refine_ops and "L5+1" in t.refine_ops[0][1]
        finally:
            inflight.finish(t)


def test_explain_analyze_refine_column(conf):
    """EXPLAIN shows a static '-' refine column; EXPLAIN ANALYZE
    surfaces the per-operator refinement summaries noted on the
    query's live ticket."""
    from mosaic_tpu.functions.context import MosaicContext
    from mosaic_tpu.obs.inflight import note_refine, ticket_observer
    from mosaic_tpu.sql import SQLSession
    try:
        mc = MosaicContext.context()
    except RuntimeError:
        mc = MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")
    s = SQLSession(mc)
    rng = np.random.default_rng(8)
    s.create_table("rpts", {"cell": rng.integers(0, 20, 500),
                            "v": rng.normal(size=500)})
    s.create_table("rz", {"index_id": np.arange(20)})
    q = ("SELECT count(*) FROM rpts JOIN rz "
         "ON rpts.cell = rz.index_id")
    plan = s.sql("EXPLAIN " + q).to_dict()
    assert all(r == "-" for r in plan["refine"])

    def obs(tkt):
        note_refine({"cells_refined": 2, "cells_flat": 3},
                    summary="L5+1: 2 refined / 3 flat cells")
    with ticket_observer(obs):
        out = s.sql("EXPLAIN ANALYZE " + q).to_dict()
    assert any("L5+1" in r for r in out["refine"])
    s.drop_table("rpts")
    s.drop_table("rz")


def test_heat_prior_calibrate_hint(conf):
    """mosaic.heat.prior=true reorders planned-join calibration to
    warm the sharded path first when the heat plane reports a skewed
    workload — an ordering hint only, answers stay bit-identical
    (calibrate itself asserts pairwise parity)."""
    import jax
    from mosaic_tpu.bench.workloads import build_workload, nyc_points
    from mosaic_tpu.obs.heat import heat
    from mosaic_tpu.parallel.pip_join import make_planned_pip_join
    _set("mosaic.heat.prior", "true")
    metrics.enable()
    heat.reset()
    heat.touch(3, rows=100_000)     # one hot cell: skew >> 2
    for c in range(8):
        heat.touch(10 + c, rows=10)
    polys, grid, res = build_workload(n_side=4, res_cells=64)
    idx = build_pip_index(polys, res, grid)
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
    pj = make_planned_pip_join(idx, grid, polys=polys, mesh=mesh)
    c0 = metrics.counter_value("heat/calibrate_hints")
    pts = nyc_points(4_096, seed=71)
    pj.calibrate(pts)               # raises on any pairwise mismatch
    assert metrics.counter_value("heat/calibrate_hints") == c0 + 1
    heat.reset()
