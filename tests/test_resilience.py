"""Resilience layer unit tests: fault plans, retry policies, the
degrade-not-die ingestion primitives, and the config/SQL satellites."""

import dataclasses
import struct

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics
from mosaic_tpu.resilience import faults
from mosaic_tpu.resilience.faults import FaultPlan, InjectedFault
from mosaic_tpu.resilience.ingest import (CodecError, ErrorSink,
                                          decode_guard)
from mosaic_tpu.resilience.retry import RetryPolicy


# ------------------------------------------------------------ fault plans

def test_spec_parsing():
    plan = FaultPlan.from_spec(
        "seed=7;site=checkpoint.*,rate=0.5,error=OSError;"
        "site=native.compile,fails=1;"
        "site=overlay.*,mode=degrade,rate=1.0,factor=8")
    assert plan.seed == 7
    assert len(plan.rules) == 3
    assert plan.rules[0].pattern == "checkpoint.*"
    assert plan.rules[0].rate == 0.5
    assert plan.rules[1].fails == 1
    assert plan.rules[2].mode == "degrade"
    assert plan.rules[2].factor == 8


@pytest.mark.parametrize("bad", [
    "site=x,mode=explode",            # unknown mode
    "site=x,error=Nope",              # unknown error type
    "rate=0.5",                       # clause without site=
    "site=x,whatever",                # item without key=value
])
def test_spec_rejects_bad_clauses(bad):
    with pytest.raises(ValueError):
        FaultPlan.from_spec(bad)


def test_fails_n_then_recovers(fault_plan):
    plan = fault_plan("seed=1;site=x.y,fails=2")
    for _ in range(2):
        with pytest.raises(OSError) as ei:
            faults.maybe_fail("x.y")
        assert isinstance(ei.value, InjectedFault)
    faults.maybe_fail("x.y")          # third call passes
    assert [s for s, _, _ in plan.injected] == ["x.y", "x.y"]


def test_rate_decisions_deterministic():
    spec = "seed=3;site=s,rate=0.5,error=ValueError"
    hits = []
    for _ in range(2):
        plan = FaultPlan.from_spec(spec)
        h = []
        for i in range(64):
            try:
                plan.maybe_fail("s")
                h.append(False)
            except ValueError:
                h.append(True)
        hits.append(h)
    assert hits[0] == hits[1]
    assert any(hits[0]) and not all(hits[0])


def test_site_pattern_scoping(fault_plan):
    fault_plan("seed=1;site=checkpoint.*,fails=1")
    faults.maybe_fail("native.compile")          # unmatched site: no-op
    with pytest.raises(OSError):
        faults.maybe_fail("checkpoint.write")


def test_corrupt_truncate_deterministic():
    spec = "seed=5;site=c,rate=1.0,mode=truncate"
    data = bytes(range(64))
    out = [FaultPlan.from_spec(spec).corrupt("c", data)
           for _ in range(2)]
    assert out[0] == out[1]
    assert len(out[0]) < len(data)


def test_corrupt_flip_changes_one_byte(fault_plan):
    plan = fault_plan("seed=5;site=c,rate=1.0,mode=flip")
    data = bytes(range(64))
    out = plan.corrupt("c", data)
    assert len(out) == len(data)
    assert sum(a != b for a, b in zip(out, data)) == 1


def test_degrade_shrinks_capacity(fault_plan):
    fault_plan("seed=2;site=overlay.*,mode=degrade,rate=1.0,factor=4")
    assert faults.degrade("overlay.bucket_cap", 100) == 25
    assert faults.degrade("overlay.dup_cap", 2) == 1    # floor of 1
    assert faults.degrade("other.site", 100) == 100


def test_disarmed_probes_are_noops(no_faults):
    assert faults.active() is None
    faults.maybe_fail("anything")
    assert faults.corrupt("anything", b"abc") == b"abc"
    assert faults.degrade("anything", 7) == 7


# ----------------------------------------------------------- retry policy

def test_retry_recovers_after_transient(fault_plan):
    plan = fault_plan("seed=1;site=r.t,fails=2")
    pol = RetryPolicy(name="t", max_attempts=3, base_delay_s=0.001,
                      jitter=0.0, retry_on=(OSError,))
    delays = []

    def fn():
        faults.maybe_fail("r.t")
        return 42

    assert pol.call(fn, sleep=delays.append) == 42
    assert delays == [0.001, 0.002]   # exponential, jitter off
    assert len(plan.injected) == 2


def test_retry_gives_up_and_reraises(fault_plan):
    fault_plan("seed=1;site=r.g,fails=9")
    pol = RetryPolicy(name="g", max_attempts=3, base_delay_s=0.0,
                      jitter=0.0)
    calls = []

    def fn():
        calls.append(1)
        faults.maybe_fail("r.g")

    with pytest.raises(OSError) as ei:
        pol.call(fn, sleep=lambda d: None)
    assert isinstance(ei.value, InjectedFault)
    assert len(calls) == 3


def test_retry_allowlist_passes_other_exceptions_through():
    pol = RetryPolicy(name="a", max_attempts=5, retry_on=(OSError,))
    calls = []

    def fn():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        pol.call(fn, sleep=lambda d: None)
    assert len(calls) == 1            # no retries for unlisted types


def test_retry_jitter_is_deterministic():
    pol = RetryPolicy(name="j", base_delay_s=0.1, jitter=0.25)
    assert pol.delay(1, seed=5) == pol.delay(1, seed=5)
    lo, hi = 0.2 * 0.75, 0.2 * 1.25
    assert lo <= pol.delay(1, seed=5) <= hi


def test_retry_on_retry_hook_and_counters(fault_plan):
    fault_plan("seed=1;site=r.h,fails=1")
    pol = RetryPolicy(name="hooked", max_attempts=2, base_delay_s=0.0,
                      jitter=0.0)
    seen = []
    metrics.enable()
    try:
        base_a = metrics.counter_value("retry/attempts/hooked")
        base_r = metrics.counter_value("retry/recovered/hooked")

        def fn():
            faults.maybe_fail("r.h")
            return "ok"

        out = pol.call(fn, on_retry=lambda e, a: seen.append((e, a)),
                       sleep=lambda d: None)
        assert out == "ok"
        assert len(seen) == 1 and seen[0][1] == 0
        assert metrics.counter_value("retry/attempts/hooked") \
            == base_a + 1
        assert metrics.counter_value("retry/recovered/hooked") \
            == base_r + 1
    finally:
        metrics.disable()


# ------------------------------------------------- degrade-not-die sinks

def test_decode_guard_locates_raw_errors():
    with pytest.raises(ValueError) as ei:
        with decode_guard(path="f.tif", feature="strip 3", offset=128):
            struct.unpack(">i", b"\x00")
    e = ei.value
    assert isinstance(e, CodecError)
    msg = str(e)
    assert "f.tif" in msg and "strip 3" in msg
    assert "byte offset 128" in msg and "error" in msg
    rec = e.record()
    assert rec.offset == 128 and rec.feature == "strip 3"


def test_decode_guard_passes_codec_errors_through():
    inner = CodecError("boom", path="a", feature="b", offset=1)
    with pytest.raises(CodecError) as ei:
        with decode_guard(path="other"):
            raise inner
    assert ei.value is inner


def test_error_sink_raise_mode_reraises():
    sink = ErrorSink("raise", driver="t")
    with pytest.raises(ValueError):
        sink.handle(ValueError("bad"))
    assert sink.dropped() == 0


def test_error_sink_skip_mode_records():
    sink = ErrorSink("skip", driver="t", path="p.bin")
    sink.handle(ValueError("bad"), feature="record 3", offset=9)
    sink.handle(CodecError("worse", feature="record 5", offset=11))
    assert sink.dropped() == 2
    assert sink.records[0].path == "p.bin"
    assert sink.records[0].feature == "record 3"
    assert sink.records[1].path == "p.bin"     # backfilled from sink
    out = []
    sink.export(out)
    assert len(out) == 2


def test_error_sink_rejects_unknown_mode():
    with pytest.raises(ValueError, match="on_error"):
        ErrorSink("explode")


def test_error_sink_default_comes_from_config():
    prev = _config.default_config()
    try:
        _config.set_default_config(
            dataclasses.replace(prev, io_on_error="skip"))
        assert ErrorSink().on_error == "skip"
    finally:
        _config.set_default_config(prev)
    assert ErrorSink().on_error == "raise"


# ------------------------------------------------------ config satellites

def test_blocksize_error_names_key():
    with pytest.raises(_config.ConfigError,
                       match="mosaic.raster.blocksize"):
        _config.MosaicConfig.from_confs(
            {"mosaic.raster.blocksize": "not-an-int"})
    with pytest.raises(_config.ConfigError,
                       match="mosaic.raster.blocksize"):
        _config.MosaicConfig.from_confs(
            {"mosaic.raster.blocksize": "-4"})


def test_device_dtype_and_exact_fallback_confs():
    cfg = _config.MosaicConfig.from_confs({
        "mosaic.device.dtype": "float64",
        "mosaic.exact.fallback": "false",
    })
    assert cfg.device_dtype == "float64"
    assert cfg.exact_fallback is False
    with pytest.raises(_config.ConfigError, match="mosaic.device.dtype"):
        _config.MosaicConfig.from_confs(
            {"mosaic.device.dtype": "float16"})


def test_io_on_error_conf():
    cfg = _config.MosaicConfig.from_confs(
        {"mosaic.io.on.error": "skip"})
    assert cfg.io_on_error == "skip"
    with pytest.raises(_config.ConfigError, match="mosaic.io.on.error"):
        _config.MosaicConfig.from_confs(
            {"mosaic.io.on.error": "maybe"})


def test_unknown_keys_open_vs_strict():
    # from_confs mirrors Spark's open conf namespace: unknown keys pass
    cfg = _config.MosaicConfig.from_confs({"spark.executor.cores": "4"})
    assert cfg == _config.MosaicConfig()
    # apply_conf is the strict programmatic/SET path: typos must raise
    with pytest.raises(_config.ConfigError, match="unknown conf key"):
        _config.apply_conf(cfg, "mosaic.raster.blocksized", "128")


def test_sql_set_statement_updates_default_config():
    from mosaic_tpu.functions.context import MosaicContext
    from mosaic_tpu.sql.engine import SQLError, SQLSession
    prev = _config.default_config()
    try:
        s = SQLSession(
            MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)"))
        out = s.sql("SET mosaic.raster.blocksize = 256")
        assert out.columns["key"] == ["mosaic.raster.blocksize"]
        assert _config.default_config().raster_blocksize == 256
        with pytest.raises(SQLError, match="mosaic.raster.blocksize"):
            s.sql("SET mosaic.raster.blocksize = banana")
        with pytest.raises(SQLError, match="unknown conf key"):
            s.sql("SET mosaic.raster.blocksized = 128")
    finally:
        _config.set_default_config(prev)


# --------------------------------------------- fixture restore semantics

def test_fault_plan_fixture_restores_previous(fault_plan):
    outer = faults.arm("seed=11;site=outer,fails=1")
    try:
        prev = faults.active()
        assert prev is outer
        # nested arm via the fixture's callable replaces...
        fault_plan("seed=12;site=inner,fails=1")
        assert faults.active() is not outer
    finally:
        faults.disarm()
