"""Fleet admission scoreboard (``serve/scoreboard.py``).

The robustness contract under test: quotas hold across processes
through one mmap'd file (over-admission impossible by construction),
a SIGKILLed holder's claims are reclaimed — by ``reap()`` within the
supervisor's interval, or immediately by admission's self-heal on a
concurrency deny — and torn slot bytes (a writer dying mid-seqlock,
or the ``scoreboard.slot`` chaos site flipping bits) degrade to a
fresh slot, never a crash.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import textwrap
import time

import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics
from mosaic_tpu.resilience import faults
from mosaic_tpu.serve.scoreboard import (RATE_WINDOW_S, Scoreboard,
                                         ScoreboardError, SlotToken)

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="fcntl/mmap scoreboard is POSIX")


@pytest.fixture
def sb_env():
    """Metrics on + clean, config restored, faults disarmed."""
    prev = _config.default_config()
    metrics.reset()
    metrics.enable()
    yield
    faults.disarm()
    _config.set_default_config(prev)
    metrics.disable()
    metrics.reset()


def _counter(name):
    return metrics.report()["counters"].get(name, 0)


# ------------------------------------------------------ basic claims

def test_admit_release_roundtrip(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=16) as sb:
        tok, deny = sb.admit("a", quota_concurrency=2, quota_qps=0)
        assert deny is None and isinstance(tok, SlotToken)
        assert sb.counts("a")["concurrency"] == 1
        assert sb.release(tok) is True
        assert sb.counts("a")["concurrency"] == 0
        # releasing twice is refused, not corrupting
        assert sb.release(tok) is False
        assert _counter("scoreboard/release_stale") == 1


def test_concurrency_quota_denies_at_limit(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=16) as sb:
        toks = [sb.admit("a", 2, 0)[0] for _ in range(2)]
        assert all(toks)
        tok, deny = sb.admit("a", 2, 0)
        assert tok is None and deny[0] == "concurrency_quota"
        # another tenant is unaffected
        tok_b, deny_b = sb.admit("b", 2, 0)
        assert deny_b is None
        sb.release(tok_b)
        for t in toks:
            sb.release(t)
        assert sb.admit("a", 2, 0)[0] is not None


def test_rate_quota_denies_with_retry_after(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=16) as sb:
        t0 = 1_000.0
        for k in range(3):
            tok, deny = sb.admit("a", 0, 3, now=t0 + k * 0.01)
            assert deny is None
            sb.release(tok)
        tok, deny = sb.admit("a", 0, 3, now=t0 + 0.05)
        assert tok is None
        reason, retry = deny
        assert reason == "rate_quota"
        assert 0.0 < retry <= RATE_WINDOW_S
        # the window slides: past RATE_WINDOW_S the claims expire
        tok, deny = sb.admit("a", 0, 3, now=t0 + RATE_WINDOW_S + 0.1)
        assert deny is None
        sb.release(tok)


def test_scoreboard_full_reason(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=2) as sb:
        assert sb.admit("a", 0, 0)[0] is not None
        assert sb.admit("b", 0, 0)[0] is not None
        tok, deny = sb.admit("c", 0, 0)
        assert tok is None and deny[0] == "scoreboard_full"
        assert _counter("scoreboard/full") == 1


def test_high_water_tracks_max_concurrency(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=16) as sb:
        toks = [sb.admit("a", 8, 0)[0] for _ in range(3)]
        for t in toks:
            sb.release(t)
        assert sb.high_water() == 3
        # high water is monotone: draining does not lower it
        tok = sb.admit("a", 8, 0)[0]
        sb.release(tok)
        assert sb.high_water() == 3


def test_reopen_attaches_and_validates(tmp_path, sb_env):
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=8) as sb:
        tok = sb.admit("a", 0, 0)[0]
        assert tok is not None
    # a second opener sees the same geometry and live claims
    with Scoreboard(path, slots=999) as sb2:   # slots from the file
        assert sb2.nslots == 8
        assert sb2.counts("a")["concurrency"] == 1
    with open(path, "r+b") as f:
        f.write(b"XXXX")
    with pytest.raises(ScoreboardError):
        Scoreboard(path)


def test_snapshot_shape(tmp_path, sb_env):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=8) as sb:
        sb.admit("a", 0, 5)
        snap = sb.snapshot()
        assert snap["slots"] == 8
        assert snap["tenants"]["a"]["concurrency"] == 1
        assert snap["tenants"]["a"]["rate"] == 1
        assert snap["free"] == 8 - 2


# ----------------------------------------- crash-recovery property

_CHILD = textwrap.dedent("""
    import json, os, sys, time
    sys.path.insert(0, {repo!r})
    from mosaic_tpu.serve.scoreboard import Scoreboard
    sb = Scoreboard({path!r})
    toks = []
    for _ in range({n}):
        tok, deny = sb.admit({tenant!r}, {quota}, 0)
        assert deny is None, deny
        toks.append(tok)
    print(json.dumps({{"pid": os.getpid(),
                       "held": len(toks)}}), flush=True)
    time.sleep(60)        # hold the claims until SIGKILLed
""")


def _spawn_holder(path, tenant, n, quota):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.Popen(
        [sys.executable, "-c",
         _CHILD.format(repo=repo, path=path, tenant=tenant,
                       n=n, quota=quota)],
        stdout=subprocess.PIPE, text=True)
    line = p.stdout.readline()
    return p, json.loads(line)


def test_killed_holder_never_over_admits(tmp_path, sb_env):
    """The property the fleet depends on: at every point between a
    holder's SIGKILL and its reap, admitted-live + admitted-dead never
    exceeds the quota (no over-admission), and the dead claims are
    reclaimed — immediately by the deny-path self-heal, and at the
    latest by the next reap tick."""
    path = str(tmp_path / "sb.bin")
    quota = 3
    with Scoreboard(path, slots=32) as sb:
        p, info = _spawn_holder(path, "a", 2, quota)
        assert info["held"] == 2
        assert sb.counts("a")["concurrency"] == 2
        # one more fits; the fourth would breach the quota
        tok3, deny = sb.admit("a", quota, 0)
        assert deny is None
        tok4, deny = sb.admit("a", quota, 0)
        assert tok4 is None and deny[0] == "concurrency_quota"

        os.kill(p.pid, signal.SIGKILL)
        p.wait(10)
        # the dead holder's 2 claims still occupy slots until healed;
        # admission self-heals on the deny path, so the very next
        # admit both reclaims them and admits — never over the quota
        tok5, deny = sb.admit("a", quota, 0)
        assert deny is None, deny
        assert _counter("scoreboard/reaped") >= 2
        assert sb.counts("a")["concurrency"] == 2   # tok3 + tok5
        assert sb.high_water() <= quota             # the witness
        sb.release(tok3)
        sb.release(tok5)


def test_reap_reclaims_within_interval(tmp_path, sb_env):
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=32) as sb:
        p, info = _spawn_holder(path, "a", 3, 0)
        assert sb.counts("a")["concurrency"] == 3
        os.kill(p.pid, signal.SIGKILL)
        p.wait(10)
        # no admission pressure: reap() alone must reclaim all three
        assert sb.reap() == 3
        assert sb.counts("a")["concurrency"] == 0
        assert sb.reap() == 0               # idempotent


def test_stale_token_release_after_reap_is_refused(tmp_path, sb_env):
    """A token whose slot was reaped (owner presumed dead) and reused
    by another tenant must not free the new holder's claim."""
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=1) as sb:
        p, _ = _spawn_holder(path, "a", 1, 0)
        os.kill(p.pid, signal.SIGKILL)
        p.wait(10)
        sb.reap()
        tok_b, deny = sb.admit("b", 0, 0)
        assert deny is None
        # forge the dead holder's view: same slot, older seq
        stale = SlotToken(tok_b.index, tok_b.seq - 2)
        assert sb.release(stale) is False
        assert sb.counts("b")["concurrency"] == 1
        assert sb.release(tok_b) is True


# --------------------------------------------------- torn-slot chaos

def test_torn_mmap_write_degrades_to_fresh_slot(tmp_path, sb_env):
    """Stomp a held slot with garbage (a writer dying mid-write):
    readers count it torn, reap re-zeroes it, admission reuses it —
    and nothing ever raises."""
    from mosaic_tpu.serve import scoreboard as _sbmod
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=4) as sb:
        tok, _ = sb.admit("a", 0, 0)
        off = _sbmod._HEADER_SIZE + tok.index * _sbmod._SLOT_SIZE
        with open(path, "r+b") as f:
            f.seek(off)
            f.write(b"\xff" * 8)            # odd seq + bad kind
        assert sb.counts("a")["concurrency"] == 0
        assert _counter("scoreboard/torn") >= 1
        sb.reap()
        # all four slots admit again — the torn one was reclaimed
        toks = [sb.admit("b", 0, 0)[0] for _ in range(4)]
        assert all(toks)


def test_chaos_site_flips_slot_reads(tmp_path, sb_env, fault_plan):
    """The ``scoreboard.slot`` fault site: a flipped read parses as
    torn (or as a phantom record the seqlock rejects) and admission
    continues; the clean path afterwards is intact."""
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=8) as sb:
        tok, _ = sb.admit("a", 0, 0)
        fault_plan("seed=31;site=scoreboard.slot,fails=8,mode=flip")
        # every slot read in this scan is damaged: degrade, not raise
        sb.counts("a")
        sb.reap()
        faults.disarm()
        # the claim survives on disk unless reap freed a torn copy;
        # either way the board still serves admissions
        tok2, deny = sb.admit("b", 4, 0)
        assert deny is None
        assert sb.release(tok2) is True


def test_truncated_chaos_read_counts_torn(tmp_path, sb_env, fault_plan):
    with Scoreboard(str(tmp_path / "sb.bin"), slots=4) as sb:
        sb.admit("a", 0, 0)
        fault_plan("seed=7;site=scoreboard.slot,fails=1,mode=truncate")
        sb.counts("a")                      # first slot read is torn
        assert _counter("scoreboard/torn") >= 1


# ------------------------------------------- admission-queue wiring

def test_admission_queue_enforces_via_scoreboard(tmp_path, sb_env):
    """Two AdmissionQueues (two would-be workers) over one scoreboard
    share one fleet-wide concurrency quota, and release() returns the
    claim for the next admit."""
    from mosaic_tpu.serve.admission import AdmissionQueue, ServeRequest
    with Scoreboard(str(tmp_path / "sb.bin"), slots=32) as sb:
        qa = AdmissionQueue(depth=8, quota_concurrency=2,
                            quota_qps=0, scoreboard=sb)
        qb = AdmissionQueue(depth=8, quota_concurrency=2,
                            quota_qps=0, scoreboard=sb)
        r1 = ServeRequest("SELECT 1", "a")
        r2 = ServeRequest("SELECT 1", "a")
        r3 = ServeRequest("SELECT 1", "a")
        assert qa.offer(r1) is None
        assert qb.offer(r2) is None       # second worker, same board
        deny = qa.offer(r3)
        assert deny is not None and deny.reason == "concurrency_quota"
        assert sb.counts("a")["concurrency"] == 2
        # take r1 through its worker lifecycle, then the slot frees
        assert qa.take(timeout=1.0) is r1
        qa.release(r1)
        assert sb.counts("a")["concurrency"] == 1
        r4 = ServeRequest("SELECT 1", "b")
        assert qb.offer(r4) is None
        qb.flush(503, "draining")
        assert sb.counts("b")["concurrency"] == 0


# ------------------------------------------- cross-process quotas

def test_two_processes_share_one_quota(tmp_path, sb_env):
    """N workers x quota Q must admit Q total, not N x Q — the bug
    the scoreboard exists to fix."""
    path = str(tmp_path / "sb.bin")
    with Scoreboard(path, slots=32) as sb:
        p, info = _spawn_holder(path, "a", 2, 4)
        try:
            assert info["held"] == 2
            # this process sees the other worker's claims: only two
            # more admissions fit under the fleet-wide quota of 4
            toks = []
            for _ in range(2):
                tok, deny = sb.admit("a", 4, 0)
                assert deny is None
                toks.append(tok)
            tok, deny = sb.admit("a", 4, 0)
            assert tok is None and deny[0] == "concurrency_quota"
            assert sb.high_water() == 4
            for t in toks:
                sb.release(t)
        finally:
            p.kill()
            p.wait(10)
