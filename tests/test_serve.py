"""The multi-tenant query service (``serve/``).

Acceptance drills for the serving PR: admission quotas deny with
Retry-After instead of melting down, overload sheds the lowest
priority first, two tenants stay isolated (one flooding tenant cannot
blow the other's latency), a client disconnect or server deadline
cancels the running query cooperatively (bounded wall, zero leaked
tickets / threads / device bytes), micro-batched point lookups are
bit-identical to serial execution while issuing fewer device launches
(asserted via the kernel ledger), SIGTERM drains instead of dropping,
and the ``serve.accept`` / ``serve.dispatch`` fault sites degrade one
request without taking the server down.
"""

import http.client
import json
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.accounting import audit, meter
from mosaic_tpu.obs.inflight import inflight
from mosaic_tpu.obs.memwatch import memwatch
from mosaic_tpu.obs.profiler import ledger
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.resilience import faults
from mosaic_tpu.serve import (AdmissionQueue, QueryServer, ServeRequest,
                              KERNEL_NAME)
from mosaic_tpu.sql import SQLSession


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture(scope="module")
def session(mc):
    s = SQLSession(mc)
    rng = np.random.default_rng(7)
    n = 50_000
    s.create_table("pts", {
        "lon": rng.uniform(-170.0, 170.0, n),
        "lat": rng.uniform(-80.0, 80.0, n),
        "v": rng.uniform(0.0, 1.0, n)})
    s.create_table("small", {
        "lon": rng.uniform(-170.0, 170.0, 256),
        "lat": rng.uniform(-80.0, 80.0, 256),
        "id": np.arange(256)})
    return s


@pytest.fixture
def serve_env():
    """Clean obs singletons + config around each server test."""
    prev = _config.default_config()
    audit.reset()
    meter.reset()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    memwatch.reset()
    yield
    faults.disarm()
    _config.set_default_config(prev)
    audit.reset()
    meter.reset()
    metrics.disable()
    metrics.reset()
    recorder.reset()
    memwatch.reset()


def _conf(**keys):
    """Apply ``mosaic.serve.*`` (or any) conf keys to the process
    default config; serve_env restores the previous config."""
    cfg = _config.default_config()
    for k, v in keys.items():
        cfg = _config.apply_conf(cfg, k.replace("_", "."), str(v))
    _config.set_default_config(cfg)


def _post(port, sql, principal="t", priority=None, deadline_ms=None,
          timeout=30.0, traceparent=None):
    """POST /query; returns (status, headers dict, body bytes)."""
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        headers = {"X-Mosaic-Principal": principal}
        if priority is not None:
            headers["X-Mosaic-Priority"] = str(priority)
        if deadline_ms is not None:
            headers["X-Mosaic-Deadline-Ms"] = str(deadline_ms)
        if traceparent is not None:
            headers["traceparent"] = traceparent
        conn.request("POST", "/query", body=sql.encode(),
                     headers=headers)
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read()
    finally:
        conn.close()


def _rows(body: bytes):
    """Decode a 200 JSON-lines response -> (columns, row list)."""
    lines = body.decode().splitlines()
    head = json.loads(lines[0])
    rows = []
    for ln in lines[1:]:
        rows.extend(json.loads(ln))
    assert len(rows) == head["rows"]
    return head["columns"], rows


_POINT_SQL = ("SELECT id, grid_longlatascellid(lon, lat, 5) AS cell "
              "FROM small")
_SLOW_SQL = ("SELECT count(*) AS n, max(v) AS mx FROM pts "
             "WHERE v > 0.25")


# ------------------------------------------------------------- basics

def test_http_basics_and_bad_requests(session, serve_env):
    with QueryServer(session, workers=2) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 200
        assert json.loads(r.read())["status"] == "ok"
        conn.close()

        status, _, body = _post(srv.port,
                                "SELECT id FROM small LIMIT 3")
        assert status == 200
        cols, rows = _rows(body)
        assert cols == ["id"] and rows == [[0], [1], [2]]

        status, _, body = _post(srv.port, "SELECT FROM nothing ((")
        assert status == 400
        status, _, body = _post(srv.port, "SELECT x FROM no_table")
        assert status == 400

        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("GET", "/nope")
        assert conn.getresponse().status == 404
        conn.close()

        # JSON body form
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("POST", "/query",
                     body=json.dumps(
                         {"sql": "SELECT id FROM small LIMIT 1"}),
                     headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        conn.close()


def test_traceparent_stitches_cross_process_trace(session, serve_env,
                                                  tmp_path):
    """A request carrying a W3C traceparent comes back with the SAME
    trace id in its response header, the worker's local trace is
    linked to it (trace_link event), and the fleet aggregator stitches
    client + server spans into one tree under that id."""
    from mosaic_tpu.obs.context import (link_traceparent, new_trace,
                                        parse_traceparent)
    from mosaic_tpu.obs.fleet import FleetAggregator
    from mosaic_tpu.obs.spool import write_spool
    from mosaic_tpu.obs.tracer import tracer
    tracer.enable()
    try:
        w3c_trace = "4bf92f3577b34da6a3ce929d0e0e4736"
        tp = f"00-{w3c_trace}-00f067aa0ba902b7-01"
        with QueryServer(session, workers=2) as srv:
            # the client half: link our own trace to the same header
            # we send, exactly like tools/loadtest.py does
            with link_traceparent(tp), new_trace("client:test"):
                with tracer.span("client/request"):
                    status, headers, body = _post(
                        srv.port, "SELECT id FROM small LIMIT 3",
                        traceparent=tp)
        assert status == 200
        # response echoes the caller's trace id with a server span id
        parsed = parse_traceparent(headers.get("traceparent", ""))
        assert parsed is not None and parsed[0] == w3c_trace
        local = headers.get("X-Mosaic-Trace", "")
        assert local.startswith("t")

        # both sides linked their local trace to the one W3C id
        links = [e for e in recorder.events("trace_link")
                 if e["w3c_trace"] == w3c_trace]
        linked_traces = {e["trace"] for e in links}
        assert local in linked_traces          # server side
        assert len(linked_traces) >= 2         # + client side
        # ... and the linked server trace actually carries spans
        spans = [e for e in recorder.events("span")
                 if e.get("trace") == local]
        assert spans, "linked query trace recorded no spans"

        # spool this process and stitch through the fleet aggregator
        assert write_spool(str(tmp_path)) is not None
        agg = FleetAggregator(str(tmp_path))
        traces = agg.stitched_traces(agg.scan())
        assert w3c_trace in traces
        tree = traces[w3c_trace]
        stitched = {s["local_trace"] for s in tree["spans"]}
        assert local in stitched and len(stitched) >= 2
        assert any(s["name"] == "client/request"
                   for s in tree["spans"])
    finally:
        tracer.disable()


def test_stats_and_dashboard_payload(session, serve_env):
    from mosaic_tpu.obs.dashboard import _server_payload
    assert _server_payload() == {"running": False}
    with QueryServer(session, workers=1) as srv:
        _post(srv.port, "SELECT id FROM small LIMIT 1", principal="a")
        st = srv.stats()
        assert st["running"] and st["workers"]["total"] == 1
        assert st["queue"]["principals"]["a"]["admitted"] == 1
        assert _server_payload()["addr"].endswith(str(srv.port))
    assert _server_payload() == {"running": False}


# ----------------------------------------------------------- admission

def test_rate_quota_denies_with_retry_after(session, serve_env):
    _conf(mosaic_serve_quota_qps=2)
    with QueryServer(session, workers=2) as srv:
        outcomes = []
        for _ in range(6):
            status, headers, body = _post(
                srv.port, "SELECT id FROM small LIMIT 1")
            outcomes.append(status)
            if status == 429:
                assert "Retry-After" in headers
                assert json.loads(body)["reason"] == "rate_quota"
        assert outcomes.count(200) >= 2       # the quota's worth ran
        assert 429 in outcomes                # the rest were refused
        assert metrics.counter_value("serve/denied_rate_quota") >= 1


def test_concurrency_quota_denies(session, serve_env, fault_plan):
    _conf(mosaic_serve_quota_concurrency=1)
    fault_plan("seed=3;site=sql.query,mode=delay,fails=1,delay_ms=400")
    with QueryServer(session, workers=2) as srv:
        results = {}

        def slow():
            results["slow"] = _post(srv.port, _SLOW_SQL,
                                    principal="heavy")[0]

        t = threading.Thread(target=slow, daemon=True)
        t.start()
        time.sleep(0.15)       # the slow query is inside its stall
        deadline = time.perf_counter() + 2.0
        denied = None
        while time.perf_counter() < deadline:
            status, headers, body = _post(
                srv.port, "SELECT id FROM small LIMIT 1",
                principal="heavy")
            if status == 429:
                denied = json.loads(body)
                assert "Retry-After" in headers
                break
            time.sleep(0.02)
        t.join(10)
        assert denied is not None and \
            denied["reason"] == "concurrency_quota"
        assert results["slow"] == 200         # the running query won


def test_queue_full_sheds_lowest_priority_first(serve_env):
    q = AdmissionQueue(depth=2, quota_concurrency=0, quota_qps=0.0)
    low1 = ServeRequest("SELECT 1", "bulk", priority=-1)
    low2 = ServeRequest("SELECT 2", "bulk", priority=-1)
    assert q.offer(low1) is None and q.offer(low2) is None
    # arriving high priority evicts the newest lowest-priority entry
    # (the oldest has waited longest and is next in line to run)
    high = ServeRequest("SELECT 3", "interactive", priority=5)
    assert q.offer(high) is None
    assert low2.future.done() and not low1.future.done()
    status, body, outcome = low2.future.result()
    assert status == 429 and outcome == "shed"
    # arriving low priority against a full same-priority queue is
    # itself the victim
    low3 = ServeRequest("SELECT 4", "bulk", priority=-1)
    deny = q.offer(low3)
    assert deny is not None and deny.reason == "shed"
    sheds = recorder.events("serve_shed")
    assert len(sheds) == 2
    assert {e["principal"] for e in sheds} == {"bulk"}
    assert metrics.counter_value("serve/shed") == 2
    snap = q.snapshot()
    assert snap["queued"] == 2
    assert snap["principals"]["bulk"]["shed"] == 2


def test_draining_queue_answers_503(serve_env):
    q = AdmissionQueue(depth=4, quota_concurrency=0, quota_qps=0.0)
    q.start_drain()
    deny = q.offer(ServeRequest("SELECT 1", "t"))
    assert deny is not None and deny.status == 503
    assert deny.reason == "draining"


# ----------------------------------------------------- tenant isolation

def test_two_tenant_isolation_under_flood(session, serve_env):
    """Tenant ``flood`` saturates its concurrency quota; tenant
    ``calm`` keeps getting prompt answers — per-tenant quotas mean one
    tenant's burst degrades that tenant, not the service."""
    _conf(mosaic_serve_quota_concurrency=2,
          mosaic_serve_workers=4, mosaic_serve_queue_depth=4)
    with QueryServer(session) as srv:
        stop = threading.Event()
        flood_status = []

        def flooder():
            while not stop.is_set():
                try:
                    flood_status.append(
                        _post(srv.port, _SLOW_SQL,
                              principal="flood")[0])
                except Exception:
                    flood_status.append(-1)

        threads = [threading.Thread(target=flooder, daemon=True)
                   for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.1)                   # let the flood build
        calm_ms = []
        for _ in range(10):
            t0 = time.perf_counter()
            status, _, _ = _post(srv.port,
                                 "SELECT id FROM small LIMIT 5",
                                 principal="calm")
            calm_ms.append((time.perf_counter() - t0) * 1e3)
            assert status == 200          # never denied: own quota
        stop.set()
        for t in threads:
            t.join(10)
        # the flooding tenant got throttled, the calm one never did
        assert flood_status.count(429) > 0
        assert metrics.counter_value("serve/denied") > 0
        calm_p99 = float(np.percentile(calm_ms, 99))
        assert calm_p99 < 5_000.0, \
            f"calm tenant p99 {calm_p99:.0f} ms under flood"
        snap = srv.queue.snapshot()["principals"]
        assert "calm" not in {p for p, v in snap.items()
                              if v["shed"] > 0}


# ------------------------------------------- cancellation + deadlines

def test_disconnect_cancels_running_query(session, serve_env,
                                          fault_plan):
    """Client drops mid-query -> the EOF watch cancels the ticket ->
    the stalled query raises at its next checkpoint (bounded wall) and
    books as ``cancelled`` with zero leaked tickets or device bytes."""
    fault_plan("seed=5;site=sql.query,mode=delay,fails=1,delay_ms=600")
    with QueryServer(session, workers=2) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("POST", "/query", body=_SLOW_SQL.encode(),
                     headers={"X-Mosaic-Principal": "dropper"})
        time.sleep(0.15)                  # query is inside the stall
        conn.close()                      # hang up without reading
        deadline = time.perf_counter() + 5.0
        rec = None
        while time.perf_counter() < deadline:
            recs = [r for r in audit.records()
                    if r["principal"] == "dropper"]
            if recs:
                rec = recs[-1]
                break
            time.sleep(0.02)
        assert rec is not None, "query never completed after hangup"
        assert rec["outcome"] == "cancelled"
        # stalled 600 ms, cancelled at the checkpoint right after —
        # nowhere near a full execution + response cycle
        assert rec["cost"]["wall_ms"] < 3_000.0
        assert len(inflight) == 0         # ticket closed
        assert memwatch.total_live() == 0 # no live device bytes
        assert memwatch.leak_count() == 0
        assert metrics.counter_value("serve/disconnects") == 1


def test_deadline_yields_504(session, serve_env, fault_plan):
    fault_plan("seed=6;site=sql.query,mode=delay,fails=1,delay_ms=500")
    with QueryServer(session, workers=2) as srv:
        status, _, body = _post(srv.port, _SLOW_SQL,
                                principal="sla", deadline_ms=100)
        assert status == 504
        assert json.loads(body)["error"] == "deadline"
        rec = [r for r in audit.records()
               if r["principal"] == "sla"][-1]
        assert rec["outcome"] == "deadline"
        assert len(inflight) == 0


# --------------------------------------------------- micro-batching

def test_microbatch_parity_and_fewer_launches(session, serve_env):
    """K concurrent compatible point lookups: one worker drains them
    into fewer device launches than queries (kernel ledger), and every
    tenant's rows are bit-identical to running its query alone."""
    _conf(mosaic_serve_workers=1, mosaic_serve_batch_window_ms=60,
          mosaic_serve_batch_max=32)
    direct = {}
    for name in ("small",):
        out = session.sql(_POINT_SQL)
        direct["small"] = {
            "id": np.asarray(out.columns["id"]),
            "cell": np.asarray(out.columns["cell"])}
    ledger.reset()
    k = 6
    with QueryServer(session) as srv:
        results = [None] * k
        barrier = threading.Barrier(k)

        def client(i):
            barrier.wait()
            results[i] = _post(srv.port, _POINT_SQL,
                               principal=f"tenant{i}")

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(k)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
    launches = sum(e["launches"] for e in ledger.report()["kernels"]
                   if e["name"] == KERNEL_NAME)
    assert 0 < launches < k, \
        f"{launches} launches for {k} batchable queries"
    assert metrics.counter_value("serve/batched_queries") == k
    for i, res in enumerate(results):
        status, _, body = res
        assert status == 200, f"tenant{i}: {res}"
        cols, rows = _rows(body)
        assert cols == ["id", "cell"]
        got = np.asarray(rows, dtype=np.int64)
        assert np.array_equal(got[:, 0], direct["small"]["id"])
        # bit parity with the serial engine path
        assert np.array_equal(got[:, 1], direct["small"]["cell"])
    # per-member accounting: every tenant metered individually
    rep = meter.report()
    for i in range(k):
        assert rep[f"tenant{i}"]["queries"] == 1
    assert len(inflight) == 0
    assert memwatch.leak_count() == 0


def test_batch_max_one_runs_serially_same_kernel(session, serve_env):
    """The serial control arm: batch.max=1 runs one launch per query
    through the same kernel, so the batched arm's fewer-launches claim
    is measured against a real baseline, not a guess."""
    _conf(mosaic_serve_workers=1, mosaic_serve_batch_max=1)
    ledger.reset()
    k = 3
    with QueryServer(session) as srv:
        for i in range(k):
            status, _, _ = _post(srv.port, _POINT_SQL,
                                 principal=f"s{i}")
            assert status == 200
    launches = sum(e["launches"] for e in ledger.report()["kernels"]
                   if e["name"] == KERNEL_NAME)
    assert launches == k


# ----------------------------------------------------------- draining

def test_drain_on_sigterm(session, serve_env, fault_plan):
    """SIGTERM -> drain: the in-flight query finishes (200), new
    admissions answer 503/refused, the drain event is flight-recorded,
    and workers exit clean."""
    fault_plan("seed=8;site=sql.query,mode=delay,fails=1,delay_ms=300")
    srv = QueryServer(session, workers=2).start()
    srv.install_sigterm_drain()
    try:
        inflight_result = {}

        def slow():
            inflight_result["status"] = _post(
                srv.port, _SLOW_SQL, principal="finisher")[0]

        t = threading.Thread(target=slow, daemon=True)
        t.start()
        time.sleep(0.1)                   # in flight, inside the stall
        os.kill(os.getpid(), signal.SIGTERM)
        t.join(15)
        assert inflight_result["status"] == 200
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline and \
                srv._thread is not None:
            time.sleep(0.05)
        # post-drain: the listener is gone (connection refused) or
        # still closing (503 draining) — either way nothing runs
        try:
            status, _, _ = _post(srv.port, _SLOW_SQL, timeout=2.0)
            assert status == 503
        except OSError:
            pass
        assert recorder.events("serve_drain")
        assert srv.pool.idle()
        assert len(inflight) == 0
    finally:
        srv.stop()


# -------------------------------------------------------------- chaos

def test_serve_accept_fault_degrades_one_connection(session, serve_env,
                                                    fault_plan):
    """An injected ``serve.accept`` fault refuses exactly that
    connection with a retryable 503; the listener keeps serving."""
    plan = fault_plan("seed=9;site=serve.accept,fails=1,error=OSError")
    with QueryServer(session, workers=1) as srv:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        assert r.status == 503
        assert "Retry-After" in dict(r.getheaders())
        conn.close()
        assert ("serve.accept", 0, "OSError") in plan.injected
        status, _, _ = _post(srv.port, "SELECT id FROM small LIMIT 1")
        assert status == 200
        assert metrics.counter_value("serve/accept_errors") == 1


def test_serve_dispatch_fault_leaks_nothing(session, serve_env,
                                            fault_plan):
    """A worker blowing up at ``serve.dispatch`` answers 500 and
    leaves no leaked ticket, no live device bytes, and a worker pool
    that still serves the next query."""
    threads_before = threading.active_count()
    plan = fault_plan(
        "seed=10;site=serve.dispatch,fails=1,error=OSError")
    with QueryServer(session, workers=2) as srv:
        status, _, body = _post(srv.port,
                                "SELECT id FROM small LIMIT 1")
        assert status == 500
        assert ("serve.dispatch", 0, "OSError") in plan.injected
        assert metrics.counter_value("serve/dispatch_errors") == 1
        assert len(inflight) == 0         # no ticket was opened
        assert memwatch.total_live() == 0
        assert memwatch.leak_count() == 0
        status, _, _ = _post(srv.port, "SELECT id FROM small LIMIT 1")
        assert status == 200              # the pool survived
    deadline = time.perf_counter() + 5.0
    while time.perf_counter() < deadline and \
            threading.active_count() > threads_before:
        time.sleep(0.05)
    assert threading.active_count() <= threads_before


def test_torn_connection_mid_response_keeps_serving(session,
                                                    serve_env):
    """A client that RSTs the socket mid-stream kills only its own
    response: the server counts it and the next request is clean."""
    with QueryServer(session, workers=2) as srv:
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=10)
        sql = ("SELECT lon, lat, v FROM pts").encode()
        sock.sendall(b"POST /query HTTP/1.1\r\n"
                     b"Host: x\r\nX-Mosaic-Principal: torn\r\n"
                     b"Content-Length: %d\r\n\r\n%s" %
                     (len(sql), sql))
        sock.recv(64)                     # read a little, then RST
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00")
        sock.close()
        deadline = time.perf_counter() + 5.0
        while time.perf_counter() < deadline:
            if [r for r in audit.records()
                    if r["principal"] == "torn"]:
                break
            time.sleep(0.02)
        status, _, _ = _post(srv.port, "SELECT id FROM small LIMIT 1")
        assert status == 200
        assert len(inflight) == 0
        assert memwatch.leak_count() == 0


# ------------------------------------------------- config validation

def test_serve_conf_keys_validate(serve_env):
    cfg = _config.default_config()
    cfg = _config.apply_conf(cfg, "mosaic.serve.port", "8817")
    assert cfg.serve_port == 8817
    with pytest.raises(ValueError):
        _config.apply_conf(cfg, "mosaic.serve.port", "70000")
    cfg = _config.apply_conf(cfg, "mosaic.serve.batch.max", "0")
    assert cfg.serve_batch_max == 0
    with pytest.raises(ValueError):
        _config.apply_conf(cfg, "mosaic.serve.batch.max", "-1")
    cfg = _config.apply_conf(cfg, "mosaic.serve.quota.qps", "2.5")
    assert cfg.serve_quota_qps == 2.5
