"""Shapefile datasource (io/shapefile.py).

Reference test shape: the OGR/shapefile reader suites load small
fixtures and check schema + geometry round trips
(datasource/ShapefileFileFormatTest etc.).  With zero egress there is
no canned fixture; the writer produces the fixture and the reader is
validated against the source geometries — plus the VERDICT round-3
criterion: read -> tessellate -> join parity vs the WKT-loaded
equivalent.
"""

import numpy as np
import pytest

from mosaic_tpu.bench.workloads import nyc_zones
from mosaic_tpu.core.geometry.wkt import read_wkt, write_wkt
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.io.shapefile import (read_shapefile, read_vector,
                                     write_shapefile)


@pytest.fixture
def zones():
    return nyc_zones(n_side=3, seed=8)


def test_shapefile_round_trip_polygons(tmp_path, zones):
    p = str(tmp_path / "zones.shp")
    cols = {"zone_id": list(range(len(zones))),
            "name": [f"z{i}" for i in range(len(zones))],
            "score": [i * 1.5 for i in range(len(zones))]}
    write_shapefile(p, zones, cols)
    geoms, attrs = read_shapefile(p)
    assert len(geoms) == len(zones)
    assert attrs["zone_id"] == cols["zone_id"]
    assert attrs["name"] == cols["name"]
    assert np.allclose(attrs["score"], cols["score"])
    # geometry round trip via WKT text equality is too strict (ring
    # winding may flip); compare canonical signed areas + vertex sets
    from mosaic_tpu.core.geometry.clip import (geometry_rings,
                                               ring_signed_area)
    for i in range(len(zones)):
        a = sum(abs(ring_signed_area(r))
                for r in geometry_rings(zones, i))
        b = sum(abs(ring_signed_area(r))
                for r in geometry_rings(geoms, i))
        assert a == pytest.approx(b, rel=1e-12)


def test_shapefile_polygon_with_hole(tmp_path):
    wkt = ["POLYGON((0 0, 10 0, 10 10, 0 10, 0 0), "
           "(3 3, 7 3, 7 7, 3 7, 3 3))"]
    src = read_wkt(wkt)
    p = str(tmp_path / "hole.shp")
    write_shapefile(p, src)
    geoms, _ = read_shapefile(p)
    from mosaic_tpu.core.geometry.clip import (geometry_rings,
                                               ring_signed_area)
    rings = geometry_rings(geoms, 0)
    assert len(rings) == 2
    total = sum(ring_signed_area(r) for r in rings)
    assert total == pytest.approx(100 - 16)


def test_shapefile_points_and_lines(tmp_path):
    pts = read_wkt(["POINT(1 2)", "POINT(-3 4.5)"])
    p = str(tmp_path / "pts.shp")
    write_shapefile(p, pts)
    geoms, _ = read_shapefile(p)
    assert np.allclose(geoms.coords[:, :2], pts.coords[:, :2])

    lines = read_wkt(["LINESTRING(0 0, 1 1, 2 0)"])
    p2 = str(tmp_path / "lines.shp")
    write_shapefile(p2, lines)
    geoms2, _ = read_shapefile(p2)
    assert np.allclose(geoms2.coords[:, :2], lines.coords[:, :2])


def test_shapefile_join_parity_vs_wkt(tmp_path, zones):
    """VERDICT round-3 criterion: shapefile -> tessellate -> PIP join
    equals the WKT-loaded path exactly."""
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn, localize,
                                              make_pip_join_fn)
    p = str(tmp_path / "zones.shp")
    write_shapefile(p, zones)
    from_shp, _ = read_shapefile(p)
    from_wkt = read_wkt(write_wkt(zones))
    grid = get_index_system("H3")
    rng = np.random.default_rng(12)
    pts = np.stack([rng.uniform(-74.25, -73.70, 20_000),
                    rng.uniform(40.50, 40.90, 20_000)], -1)
    outs = []
    for polys in (from_shp, from_wkt):
        idx = build_pip_index(polys, 9, grid)
        fn = jax.jit(make_pip_join_fn(idx, grid))
        z, u = fn(jnp.asarray(localize(idx, pts)))
        outs.append(host_recheck_fn(idx)(pts, np.asarray(z),
                                         np.asarray(u)))
    assert np.array_equal(outs[0], outs[1])


def test_read_vector_driver_dispatch(tmp_path, zones):
    p = str(tmp_path / "zones.shp")
    write_shapefile(p, zones)
    g1, _ = read_vector(p)
    assert len(g1) == len(zones)
    # wkt driver
    wp = tmp_path / "zones.wkt"
    wp.write_text("\n".join(write_wkt(zones)))
    g2, _ = read_vector(str(wp))
    assert len(g2) == len(zones)
    # geojson FeatureCollection
    import json
    from mosaic_tpu.core.geometry.geojson import write_geojson
    feats = [{"type": "Feature", "geometry": json.loads(j),
              "properties": {"i": i}}
             for i, j in enumerate(write_geojson(zones))]
    jp = tmp_path / "zones.geojson"
    jp.write_text(json.dumps({"type": "FeatureCollection",
                              "features": feats}))
    g3, cols = read_vector(str(jp))
    assert len(g3) == len(zones) and cols["i"] == list(range(len(zones)))
    with pytest.raises(ValueError):
        read_vector("nope.xyz")


def test_shapefile_rejects_garbage(tmp_path):
    p = tmp_path / "bad.shp"
    p.write_bytes(b"not a shapefile at all")
    with pytest.raises(ValueError):
        read_shapefile(str(p))
