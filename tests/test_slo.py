"""SLO burn-rate alerting and the ops dashboard.

Covers the burn-rate math per objective kind, the exactly-one-alert
breach-episode contract, the acceptance drill — a fault-plan-injected
slow query deterministically raises ONE alert (flight-recorder event +
``obs/alerts_active`` gauge + ``mosaic_slo_*`` OpenMetrics line +
dashboard JSON) while a clean run raises zero — and the stoppable
``ServerHandle`` shared by the scrape server and the dashboard.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import mosaic_tpu as mos
from mosaic_tpu.obs import (metrics, recorder, serve_dashboard,
                            serve_metrics, timeseries, to_openmetrics,
                            tracer)
from mosaic_tpu.obs.slo import (SLObjective, SLOMonitor,
                                default_objectives, monitor)
from mosaic_tpu.obs.timeseries import TimeSeriesStore


@pytest.fixture
def telemetry():
    """Fresh global telemetry plane (store + monitor + registry +
    recorder) for one test; everything restored after."""
    timeseries.reset()
    monitor.reset(default_objectives())
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    tracer.reset()
    tracer.enable()
    yield
    tracer.disable()
    tracer.reset()
    recorder.reset()
    metrics.disable()
    metrics.reset()
    monitor.reset(default_objectives())
    timeseries.reset()


@pytest.fixture
def session():
    ctx = mos.enable_mosaic("CUSTOM(-180,180,-90,90,2,360,180)")
    s = mos.SQLSession(ctx)
    s.create_table("pts", {"x": np.arange(100.0),
                           "y": np.arange(100.0) / 10.0})
    return s


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return r.read().decode("utf-8")


# --------------------------------------------------- burn-rate math

def test_latency_needs_both_windows_hot():
    store = TimeSeriesStore()
    obj = SLObjective(name="lat", kind="latency", series="q_ms",
                      threshold_ms=100.0, objective=0.95,
                      windows=(60.0, 300.0))
    now = 1000.0
    # long window: 100 good points; short window: 5 bad points
    for i in range(100):
        store.record("q_ms", 10.0, ts=700.0 + 2 * i)
    for i in range(5):
        store.record("q_ms", 500.0, ts=955.0 + i)
    res = obj.evaluate(store, now)
    # short window is fully bad, long window holds under budget
    assert res["short"] == 1.0
    assert res["long"] == pytest.approx(5 / 105)
    assert res["budget"] == pytest.approx(0.05)
    assert not res["breached"]
    # more sustained badness pushes the long window over too
    for i in range(10):
        store.record("q_ms", 500.0, ts=990.0 + i / 2.0)
    assert obj.evaluate(store, now)["breached"]


def test_error_rate_uses_counter_rates():
    store = TimeSeriesStore()
    obj = SLObjective(name="err", kind="error_rate", bad="bad",
                      total="total", objective=0.90,
                      windows=(60.0, 300.0))
    now = 1000.0
    # total grows 1/s, bad grows 0.04/s -> 4% < 10% budget
    for i in range(301):
        store.record("total", float(i), ts=700.0 + i)
        store.record("bad", 0.04 * i, ts=700.0 + i)
    res = obj.evaluate(store, now)
    assert res["short"] == pytest.approx(0.04, rel=1e-6)
    assert not res["breached"]
    # bad accelerating to 0.5/s trips both windows
    store2 = TimeSeriesStore()
    for i in range(301):
        store2.record("total", float(i), ts=700.0 + i)
        store2.record("bad", 0.5 * i, ts=700.0 + i)
    assert obj.evaluate(store2, now)["breached"]


def test_counter_rate_is_a_rate_ceiling():
    store = TimeSeriesStore()
    obj = SLObjective(name="storm", kind="counter_rate",
                      series="compiles", max_rate=2.0,
                      windows=(60.0, 300.0))
    now = 1000.0
    for i in range(301):                    # 5 compiles/s sustained
        store.record("compiles", 5.0 * i, ts=700.0 + i)
    res = obj.evaluate(store, now)
    assert res["short"] == pytest.approx(2.5, rel=1e-6)   # 5/2
    assert res["breached"]
    slow = TimeSeriesStore()
    for i in range(301):                    # 1/s stays under
        slow.record("compiles", float(i), ts=700.0 + i)
    assert not obj.evaluate(slow, now)["breached"]


def test_gauge_max_is_a_ceiling():
    store = TimeSeriesStore()
    obj = SLObjective(name="skew", kind="gauge_max", series="skew",
                      ceiling=8.0, windows=(60.0, 300.0))
    now = 1000.0
    for i in range(301):
        store.record("skew", 10.0, ts=700.0 + i)
    assert obj.evaluate(store, now)["breached"]
    ok = TimeSeriesStore()
    for i in range(301):
        ok.record("skew", 3.0, ts=700.0 + i)
    assert not obj.evaluate(ok, now)["breached"]


def test_latency_min_points_floor():
    store = TimeSeriesStore()
    obj = SLObjective(name="lat", kind="latency", series="q_ms",
                      threshold_ms=100.0, objective=0.95,
                      min_points=3, windows=(60.0, 300.0))
    store.record("q_ms", 500.0, ts=999.0)
    store.record("q_ms", 500.0, ts=999.5)
    # 2 points, 100% bad — but below the evidence floor
    assert not obj.evaluate(store, 1000.0)["breached"]
    store.record("q_ms", 500.0, ts=999.8)
    assert obj.evaluate(store, 1000.0)["breached"]


def test_unknown_kind_rejected():
    with pytest.raises(ValueError):
        SLObjective(name="x", kind="vibes")


# ------------------------------------------ breach-episode contract

def test_monitor_alerts_exactly_once_then_recovers(telemetry):
    store = TimeSeriesStore()
    mon = SLOMonitor(objectives=[SLObjective(
        name="lat", kind="latency", series="q_ms",
        threshold_ms=100.0, objective=0.95, windows=(60.0, 300.0))],
        store=store)
    for i in range(10):
        store.record("q_ms", 500.0, ts=995.0 + i / 2.0)
    trans = mon.evaluate(now=1000.0)
    assert [t["transition"] for t in trans] == ["breach"]
    assert mon.alerts_active() == 1 and mon.breach_count() == 1
    assert metrics.gauge_value("obs/alerts_active") == 1.0
    assert metrics.gauge_value("slo/active/lat") == 1.0
    assert metrics.counter_value("slo/breaches") == 1
    # still breached: silent (no alert storm)
    assert mon.evaluate(now=1001.0) == []
    assert len(recorder.events("slo_breach")) == 1
    # data ages out of both windows -> one recovery transition
    trans = mon.evaluate(now=2000.0)
    assert [t["transition"] for t in trans] == ["recovery"]
    assert mon.alerts_active() == 0
    assert metrics.gauge_value("obs/alerts_active") == 0.0
    assert metrics.gauge_value("slo/active/lat") == 0.0
    assert len(recorder.events("slo_recovered")) == 1
    # breach_count keeps the historical total
    assert mon.breach_count() == 1


def test_monitor_reset_clears_gauges(telemetry):
    store = TimeSeriesStore()
    mon = SLOMonitor(objectives=[SLObjective(
        name="skew", kind="gauge_max", series="s", ceiling=1.0,
        windows=(60.0, 300.0))], store=store)
    store.record("s", 5.0, ts=999.0)
    mon.evaluate(now=1000.0)
    assert metrics.gauge_value("obs/alerts_active") == 1.0
    mon.reset()
    assert mon.alerts_active() == 0
    assert metrics.gauge_value("obs/alerts_active") == 0.0


# --------------------------------------- the acceptance-criteria drill

def test_injected_slow_query_raises_exactly_one_alert(
        telemetry, session, fault_plan):
    """A fault-plan delay on ``sql.query`` must deterministically fire
    ONE sql-latency alert: recorder event, ``obs/alerts_active``
    gauge, ``mosaic_slo_*`` OpenMetrics lines, dashboard JSON."""
    monitor.reset([SLObjective(
        name="sql_latency", kind="latency", series="sql/query_ms",
        threshold_ms=250.0, objective=0.95, min_points=1,
        windows=(60.0, 300.0))])
    fault_plan("site=sql.query,mode=delay,fails=1,delay_ms=500")
    session.sql("SELECT x FROM pts")         # stalled 500 ms: bad
    session.sql("SELECT x FROM pts")         # clean: fast
    trans = monitor.evaluate()
    assert [t["transition"] for t in trans] == ["breach"]
    assert [t["name"] for t in trans] == ["sql_latency"]
    # exactly one: re-evaluating while still breached stays silent
    assert monitor.evaluate() == []
    assert len(recorder.events("slo_breach")) == 1
    assert monitor.alerts_active() == 1
    assert metrics.gauge_value("obs/alerts_active") == 1.0
    txt = to_openmetrics()
    assert "mosaic_slo_active_sql_latency 1" in txt
    assert "mosaic_slo_breaches_total 1" in txt
    assert "mosaic_obs_alerts_active 1" in txt
    # the dashboard reports the same alert over HTTP
    handle = serve_dashboard(port=0)
    try:
        alerts = json.loads(_get(
            f"http://127.0.0.1:{handle.port}/api/alerts"))
        assert [a["name"] for a in alerts["active"]] == ["sql_latency"]
        assert len(alerts["recent_breaches"]) == 1
        summary = json.loads(_get(
            f"http://127.0.0.1:{handle.port}/api/summary"))
        assert summary["alerts_active"] == 1
    finally:
        handle.close()


def test_clean_run_raises_zero_alerts(telemetry, session, no_faults):
    """Default objectives + ordinary traffic: nothing fires."""
    for _ in range(3):
        session.sql("SELECT x, y FROM pts WHERE x < 50")
    assert monitor.evaluate() == []
    assert monitor.alerts_active() == 0
    assert metrics.gauge_value("obs/alerts_active") == 0.0
    assert recorder.events("slo_breach") == []
    assert "mosaic_slo_breaches_total" not in to_openmetrics()
    # queries did land in the time-series plane
    assert timeseries.window_stats("sql/query_ms", 300)["count"] == 3


# --------------------------------------------- server handle + pages

def test_serve_metrics_handle_start_scrape_stop(telemetry):
    metrics.count("handle/test", 7)
    handle = serve_metrics(port=0)
    try:
        assert handle.port > 0
        body = _get(f"http://127.0.0.1:{handle.port}/metrics")
        assert "mosaic_handle_test_total 7" in body
    finally:
        handle.close()
    handle.close()                            # idempotent
    with pytest.raises((urllib.error.URLError, ConnectionError,
                        OSError)):
        urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/metrics", timeout=2)


def test_dashboard_endpoints(telemetry, session):
    session.sql("SELECT x FROM pts")
    timeseries.record("demo/series", 1.5)
    handle = serve_dashboard(port=0)
    base = f"http://127.0.0.1:{handle.port}"
    try:
        page = _get(base + "/")
        assert "ops dashboard" in page and "/api/summary" in page
        summary = json.loads(_get(base + "/api/summary"))
        assert summary["metrics_enabled"] is True
        assert summary["series"] >= 1
        names = json.loads(_get(base + "/api/series?prefix=demo/"))
        assert names["names"] == ["demo/series"]
        ts = json.loads(_get(
            base + "/api/timeseries?name=demo/series&window=60"))
        assert ts["found"] and ts["stats"]["count"] == 1
        missing = json.loads(_get(
            base + "/api/timeseries?name=nope&window=60"))
        assert missing["found"] is False
        for route in ("/api/alerts", "/api/traces", "/api/planner",
                      "/api/devices", "/metrics"):
            assert _get(base + route)
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope", timeout=5)
    finally:
        handle.close()
