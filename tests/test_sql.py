"""SQL surface tests.

Reference counterparts: sql/extensions/MosaicSQL.scala (function surface
reachable from SQL), sql/Prettifier.scala, and the Quickstart notebook's
PIP-join query shape (notebooks/examples/python/Quickstart/
QuickstartNotebook.ipynb): cell-id equi-join + ``is_core OR
st_contains(wkb, geom)`` filter.
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.array import GeometryArray, GeometryBuilder
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.sql import (SQLError, SQLParseError, SQLSession, parse,
                            prettified)


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture(scope="module")
def session(mc):
    return SQLSession(mc)


def _zones() -> GeometryArray:
    b = GeometryBuilder()
    b.add_polygon(np.array([[0.0, 0.0], [10.0, 0.0], [10.0, 10.0],
                            [0.0, 10.0], [0.0, 0.0]]))
    b.add_polygon(np.array([[10.0, 0.0], [20.0, 0.0], [20.0, 10.0],
                            [10.0, 10.0], [10.0, 0.0]]))
    return b.finish()


def _points(n=200, seed=7) -> GeometryArray:
    rng = np.random.default_rng(seed)
    xy = np.column_stack([rng.uniform(0.5, 19.5, n),
                          rng.uniform(0.5, 9.5, n)])
    return GeometryArray.from_points(xy)


def test_select_where_order_limit(session):
    session.create_table("t", {
        "a": np.array([3.0, 1.0, 2.0, 4.0]),
        "b": np.array([1, 2, 3, 4], np.int64)})
    out = session.sql("SELECT a, b FROM t WHERE a > 1.5 ORDER BY a DESC "
                      "LIMIT 2")
    assert out.columns["a"].tolist() == [4.0, 3.0]
    assert out.columns["b"].tolist() == [4, 1]


def test_expressions_and_aliases(session):
    session.create_table("e", {"x": np.array([1.0, 2.0, 3.0])})
    out = session.sql("SELECT x * 2 + 1 AS y, -x AS neg FROM e")
    assert out.columns["y"].tolist() == [3.0, 5.0, 7.0]
    assert out.columns["neg"].tolist() == [-1.0, -2.0, -3.0]


def test_st_functions_from_sql(session):
    session.create_table("geoms", {"geom": _zones(),
                                   "name": ["west", "east"]})
    out = session.sql("SELECT name, st_area(geom) AS area FROM geoms")
    assert out.columns["area"].tolist() == [100.0, 100.0]
    out2 = session.sql("SELECT st_xmin(geom) AS x0 FROM geoms "
                       "WHERE name = 'east'")
    assert out2.columns["x0"].tolist() == [10.0]


def test_group_by_aggregates(session):
    session.create_table("g", {
        "k": np.array([1, 1, 2, 2, 2], np.int64),
        "v": np.array([1.0, 3.0, 5.0, 7.0, 9.0])})
    out = session.sql("SELECT k, count(*) AS n, avg(v) AS m, sum(v) s "
                      "FROM g GROUP BY k ORDER BY k")
    assert out.columns["n"].tolist() == [2, 3]
    assert out.columns["m"].tolist() == [2.0, 7.0]
    assert out.columns["s"].tolist() == [4.0, 21.0]


def test_tessellate_explode_generator(session, mc):
    session.create_table("zones", {"geom": _zones(),
                                   "zid": np.array([10, 20], np.int64)})
    out = session.sql("SELECT zid, grid_tessellateexplode(geom, 3) "
                      "FROM zones")
    assert set(out.columns) == {"zid", "is_core", "index_id", "wkb"}
    # parity vs the Python-level call
    chips = mc.grid_tessellate(_zones(), 3, keep_core_geom=False)
    assert len(out) == len(chips)
    assert np.array_equal(np.sort(out.columns["index_id"]),
                          np.sort(chips.cell_id))
    # zid replicates along the explosion
    zid = out.columns["zid"]
    assert set(zid.tolist()) == {10, 20}


def test_quickstart_pip_join_in_sql(session, mc):
    """The reference Quickstart join, written in SQL against this engine,
    must equal the host-truth point-in-polygon assignment."""
    zones, pts = _zones(), _points()
    res = 3
    session.create_table("zones", {"geom": zones,
                                   "zid": np.arange(2, dtype=np.int64)})
    session.create_table("chips", session.sql(
        "SELECT zid, grid_tessellateexplode(geom, 3) FROM zones"
    ).to_dict())
    session.create_table("pts", {
        "pgeom": pts,
        "cell": mc.grid_pointascellid(pts, res),
        "pid": np.arange(len(pts), dtype=np.int64)})
    out = session.sql(
        "SELECT pid, zid FROM pts JOIN chips ON pts.cell = chips.index_id "
        "WHERE is_core OR st_contains(wkb, pgeom)")
    # host truth: x < 10 -> zone 0 else zone 1 (points stay off borders)
    xy = pts.coords
    want = (xy[:, 0] >= 10.0).astype(np.int64)
    got = np.full(len(pts), -1, np.int64)
    got[out.columns["pid"]] = out.columns["zid"]
    assert np.array_equal(got, want)
    # every point matched exactly once
    assert len(out) == len(pts)


def test_kring_explode_generator(session, mc):
    cells = mc.grid_pointascellid(_points(5), 3)
    session.create_table("c", {"cell": cells,
                               "row": np.arange(5, dtype=np.int64)})
    out = session.sql("SELECT row, grid_cellkringexplode(cell, 1) AS nbr "
                      "FROM c")
    src, flat = mc.grid_cellkringexplode(cells, 1)
    assert np.array_equal(out.columns["nbr"], flat)
    assert np.array_equal(out.columns["row"], src)


def test_join_requires_equality(session):
    session.create_table("a1", {"x": np.array([1, 2], np.int64)})
    session.create_table("b1", {"y": np.array([1, 2], np.int64)})
    with pytest.raises(SQLError):
        session.sql("SELECT x FROM a1 JOIN b1 ON x < y")


def test_parse_errors():
    with pytest.raises(SQLParseError):
        parse("SELECT FROM t")
    with pytest.raises(SQLParseError):
        parse("SELECT a FROM t WHERE ???")


def test_unknown_function_and_table(session):
    session.create_table("u", {"x": np.array([1.0])})
    with pytest.raises(SQLError):
        session.sql("SELECT nope_fn(x) FROM u")
    with pytest.raises(SQLError):
        session.sql("SELECT x FROM missing_table")


def test_prettified(session):
    session.create_table("p", {"geom": _zones(),
                               "blob": [b"\x01\x02\x03" * 10, b"\x04"],
                               "v": np.array([1.234567890123, 2.0])})
    txt = prettified(session.table("p"))
    assert "POLYGON" in txt
    assert "0x" in txt and "…" in txt
    assert txt.count("\n") >= 5


def test_star_and_qualified_columns(session):
    session.create_table("s1", {"k": np.array([1, 2], np.int64),
                                "v": np.array([10.0, 20.0])})
    session.create_table("s2", {"k": np.array([2, 1], np.int64),
                                "w": np.array([7.0, 8.0])})
    out = session.sql("SELECT s1.k AS k, v, w FROM s1 JOIN s2 "
                      "ON s1.k = s2.k ORDER BY k")
    assert out.columns["k"].tolist() == [1, 2]
    assert out.columns["w"].tolist() == [8.0, 7.0]
    allc = session.sql("SELECT * FROM s1")
    assert set(allc.columns) == {"k", "v"}


def test_geometry_kring_explode_functions(mc):
    g = _zones()
    src, cells = mc.grid_geometrykringexplode(g, 3, 1)
    assert len(src) == len(cells) and len(cells) > 0
    loops_src, loops = mc.grid_geometrykloopexplode(g, 3, 2)
    ring1 = set(cells[src == 0].tolist())
    loop2 = set(loops[loops_src == 0].tolist())
    assert ring1.isdisjoint(loop2)      # loop excludes interior ring


def test_function_errors_pass_through(session):
    """A ValueError raised INSIDE a registered function must surface
    as-is, not be relabelled 'unknown function' (review finding)."""
    session.create_table("w", {"s": ["NOT A WKT"]})
    with pytest.raises(ValueError, match="WKT parse error"):
        session.sql("SELECT st_geomfromwkt(s) AS g FROM w")


def test_self_join_requires_aliases(session):
    session.create_table("sj", {"k": np.array([1, 2], np.int64)})
    with pytest.raises(SQLError, match="distinct aliases"):
        session.sql("SELECT k FROM sj JOIN sj ON sj.k = sj.k")
    out = session.sql("SELECT a.k AS ka, b.k AS kb FROM sj a JOIN sj b "
                      "ON a.k = b.k ORDER BY ka")
    assert out.columns["ka"].tolist() == [1, 2]
    assert out.columns["kb"].tolist() == [1, 2]


def test_explode_with_where_filter(session, mc):
    """The docstring's flagship shape: WHERE runs AFTER the explode so
    filters can reference generated columns — and the projection must
    read generator columns from the FILTERED env (round-4 ADVICE high:
    a WHERE that dropped rows raised 'ragged columns')."""
    session.create_table("zones", {"geom": _zones(),
                                   "zid": np.array([10, 20], np.int64)})
    allrows = session.sql("SELECT zid, grid_tessellateexplode(geom, 3) "
                          "FROM zones")
    core = session.sql("SELECT zid, grid_tessellateexplode(geom, 3) "
                       "FROM zones WHERE is_core")
    ncore = int(np.asarray(allrows.columns["is_core"]).sum())
    assert len(core) == ncore
    assert np.asarray(core.columns["is_core"]).all()
    # generated + carried columns stay row-aligned after the filter
    assert len(core.columns["zid"]) == len(core.columns["index_id"])


def test_group_by_rejects_ungrouped_column(session):
    session.create_table("g2", {
        "k": np.array([1, 1, 2], np.int64),
        "v": np.array([1.0, 2.0, 3.0]),
    })
    import pytest as _pytest
    from mosaic_tpu.sql.engine import SQLError
    with _pytest.raises(SQLError, match="GROUP BY"):
        session.sql("SELECT v, count(*) FROM g2 GROUP BY k")


def test_count_column_skips_nulls(session):
    session.create_table("g3", {
        "k": np.array([1, 1, 2], np.int64),
        "v": np.array([1.0, np.nan, 3.0]),
    })
    out = session.sql("SELECT k, count(v) AS n FROM g3 GROUP BY k "
                      "ORDER BY k")
    assert out.columns["n"].tolist() == [1, 1]


def test_order_by_non_projected_column(session):
    session.create_table("g4", {
        "a": np.array([3, 1, 2], np.int64),
        "b": np.array([30, 10, 20], np.int64),
    })
    out = session.sql("SELECT b FROM g4 ORDER BY a")
    assert out.columns["b"].tolist() == [10, 20, 30]


def test_left_join(session):
    session.create_table("l", {"k": np.array([1, 2, 3], np.int64),
                               "a": np.array([10., 20., 30.])})
    session.create_table("r", {"k": np.array([1, 3], np.int64),
                               "b": np.array([100., 300.])})
    out = session.sql("SELECT l.k, a, b FROM l LEFT JOIN r "
                      "ON l.k = r.k ORDER BY a")
    assert out.columns["k"].tolist() == [1, 2, 3]
    b = np.asarray(out.columns["b"], np.float64)
    assert b[0] == 100.0 and np.isnan(b[1]) and b[2] == 300.0
    # LEFT OUTER spelling too
    out2 = session.sql("SELECT l.k FROM l LEFT OUTER JOIN r "
                       "ON l.k = r.k")
    assert len(out2) == 3


def test_left_join_null_semantics(session):
    import pytest as _pytest
    from mosaic_tpu.sql.engine import SQLError
    session.create_table("l2", {"k": np.array([1, 2, 3], np.int64),
                                "a": np.array([10., 20., 30.])})
    # empty right side: every row unmatched, still 3 output rows
    session.create_table("r0", {"k": np.empty(0, np.int64),
                                "b": np.empty(0)})
    out = session.sql("SELECT l2.k, b FROM l2 LEFT JOIN r0 "
                      "ON l2.k = r0.k")
    assert len(out) == 3
    assert all(v is None or (isinstance(v, float) and np.isnan(v))
               for v in list(out.columns["b"]))
    # int64 ids survive exactly through null-bearing columns
    big = 613196571542765567
    session.create_table("rc", {"k": np.array([1], np.int64),
                                "cell": np.array([big], np.int64)})
    out2 = session.sql("SELECT l2.k, cell FROM l2 LEFT JOIN rc "
                       "ON l2.k = rc.k ORDER BY a")
    assert list(out2.columns["cell"])[0] == big
    assert list(out2.columns["cell"])[1] is None
    # aggregates skip nulls; all-null group -> NaN
    session.create_table("rv", {"k": np.array([1, 3], np.int64),
                                "v": np.array([100., 300.])})
    session.create_table("lj", session.sql(
        "SELECT l2.k AS k, v FROM l2 LEFT JOIN rv ON l2.k = rv.k"
    ).to_dict())
    agg = session.sql("SELECT sum(v) AS s, count(v) AS n FROM lj")
    assert agg.columns["s"].tolist() == [400.0]
    assert agg.columns["n"].tolist() == [2]
    # geometry columns refuse null rows loudly
    import mosaic_tpu as mos
    session.create_table("rg", {"k": np.array([1], np.int64),
                                "g": mos.read_wkt(["POINT (0 0)"])})
    with _pytest.raises(SQLError, match="null"):
        session.sql("SELECT l2.k, g FROM l2 LEFT JOIN rg "
                    "ON l2.k = rg.k")


def test_group_by_having(session):
    session.create_table("h1", {
        "k": np.array([1, 1, 2, 2, 2, 3], np.int64),
        "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])})
    out = session.sql("SELECT k, count(*) AS n, sum(v) AS s FROM h1 "
                      "GROUP BY k HAVING count(*) >= 2 ORDER BY k")
    assert out.columns["k"].tolist() == [1, 2]
    assert out.columns["n"].tolist() == [2, 3]
    out2 = session.sql("SELECT k FROM h1 GROUP BY k "
                       "HAVING sum(v) > 3 AND k < 3")
    assert sorted(np.asarray(out2.columns["k"]).tolist()) == [2]


def test_having_edge_cases(session):
    import pytest as _pytest
    from mosaic_tpu.sql.engine import SQLError
    session.create_table("h2", {
        "k": np.array([1, 1, 2], np.int64),
        "v": np.array([1.0, 2.0, 3.0])})
    # HAVING without GROUP BY: whole-table implicit group
    out = session.sql("SELECT count(*) AS n FROM h2 HAVING count(*) > 5")
    assert len(out) == 0
    out2 = session.sql("SELECT count(*) AS n FROM h2 HAVING count(*) > 2")
    assert out2.columns["n"].tolist() == [3]
    # unary minus inside HAVING
    out3 = session.sql("SELECT k FROM h2 GROUP BY k "
                       "HAVING -sum(v) < -2.5")
    assert sorted(np.asarray(out3.columns["k"]).tolist()) == [1, 2]
    # ungrouped bare column must raise, not take first rows
    with _pytest.raises(SQLError, match="GROUP BY"):
        session.sql("SELECT k FROM h2 GROUP BY k HAVING v > 1.5")


def test_explain_and_explain_analyze(session):
    # cold planner: the fused-column asserts below rely on the static
    # crossover, not coefficients trained by earlier tests
    from mosaic_tpu.sql.planner import planner
    planner.reset()
    session.create_table("ea", {
        "k": np.array([1, 2, 3, 4], np.int64),
        "v": np.array([1.0, 2.0, 3.0, 4.0])})
    # EXPLAIN: static operator plan, nothing executed
    plan = session.sql("EXPLAIN SELECT k FROM ea WHERE v > 1.5")
    ops = list(plan.columns["operator"])
    assert ops == ["scan", "filter", "project"]
    assert "rows" not in plan.columns
    # the planner annotates each operator with its chosen strategy
    strategies = list(plan.columns["strategy"])
    assert len(strategies) == len(ops)
    assert all(isinstance(s, str) and s for s in strategies)
    # EXPLAIN ANALYZE: executed plan with per-operator rows + wall time
    out = session.sql("EXPLAIN ANALYZE SELECT k, v FROM ea "
                      "WHERE v > 1.5 ORDER BY v DESC LIMIT 2")
    ops = list(out.columns["operator"])
    assert ops == ["scan", "filter", "project", "order", "limit"]
    rows = dict(zip(ops, out.columns["rows"].tolist()))
    assert rows["scan"] == 4 and rows["filter"] == 3
    assert rows["limit"] == 2
    assert out.columns["rows"].dtype == np.int64
    times = out.columns["time_ms"]
    assert len(times) == 5 and all(t >= 0.0 for t in times.tolist())
    # est_rows: the planner's pre-pass cardinality estimate next to
    # the observed rows (-1 when the planner had no estimate)
    est = out.columns["est_rows"]
    assert est.dtype == np.int64 and len(est) == 5
    erows = dict(zip(ops, est.tolist()))
    assert erows["scan"] == 4       # scan cardinality is exact
    # aggregates show as an aggregate operator with group-key detail
    agg = session.sql("EXPLAIN ANALYZE SELECT k, count(*) AS n "
                      "FROM ea GROUP BY k")
    aops = list(agg.columns["operator"])
    assert "aggregate" in aops and "project" not in aops
    arows = dict(zip(aops, agg.columns["rows"].tolist()))
    assert arows["aggregate"] == 4
    # single-device queries carry the sharded columns as zeros
    assert agg.columns["all_to_all_bytes"].tolist() == [0, 0]
    assert agg.columns["shard_skew"].tolist() == [0.0, 0.0]
    # ... and an empty per-device attribution cell ("-"): nothing
    # charged busy time to a device during these host-only stages
    assert list(agg.columns["device_ms"]) == ["-", "-"]
    assert len(out.columns["device_ms"]) == len(ops)
    # fused column: group id or "-".  A 4-row table sits far below the
    # fusion crossover (and GROUP BY is statically ineligible), so
    # every operator here dispatches alone
    assert list(plan.columns["fused"]) == ["-", "-", "-"]
    assert list(out.columns["fused"]) == ["-"] * len(ops)
    assert list(agg.columns["fused"]) == ["-", "-"]


def test_explain_est_bytes_and_peak_bytes(session):
    """EXPLAIN carries the planner's pre-pass byte estimate
    (``est_rows`` x source row width, -1 when unknown); EXPLAIN
    ANALYZE carries the observed per-stage device-memory allocation
    from the ledger (0 for host-only stages)."""
    session.create_table("eb", {
        "k": np.arange(8, dtype=np.int64),        # 8 B
        "v": np.arange(8, dtype=np.float64)})     # + 8 B = 16 B/row
    plan = session.sql("EXPLAIN SELECT k FROM eb WHERE v > 1.5")
    est = dict(zip(plan.columns["operator"],
                   plan.columns["est_bytes"].tolist()))
    assert plan.columns["est_bytes"].dtype == np.int64
    assert est["scan"] == 8 * 16      # scan cardinality is exact
    assert all(b == -1 or b >= 0 for b in est.values())
    out = session.sql("EXPLAIN ANALYZE SELECT k FROM eb WHERE v > 1.5")
    peak = out.columns["peak_bytes"]
    assert peak.dtype == np.int64 and len(peak) == 3
    # host-only stages allocate no device memory; nothing negative
    assert all(b >= 0 for b in peak.tolist())
    session.drop_table("eb")


def test_explain_fused_column(session):
    """EXPLAIN/EXPLAIN ANALYZE surface the fusion group id on every
    member operator once the query clears the fusion crossover."""
    from mosaic_tpu import config as _config
    rng = np.random.default_rng(7)
    n = 4096
    session.create_table("eaf", {
        "a": rng.normal(size=n),
        "b": rng.integers(0, 9, size=n)})
    q = ("SELECT count(*) AS n, max(a) AS mx FROM eaf "
         "WHERE a > 0.0 AND b < 5")
    # pin fused on: the planner singleton's learned coefficients are
    # process-global, so the auto decision depends on test order
    prev = _config.default_config()
    _config.set_default_config(_config.apply_conf(
        prev, "mosaic.planner.force.fusion", "on"))
    try:
        plan = session.sql("EXPLAIN " + q)
        fused = dict(zip(plan.columns["operator"],
                         plan.columns["fused"]))
        assert fused["filter"] == fused["aggregate"] == "g1"
        assert fused["scan"] == "-"
        out = session.sql("EXPLAIN ANALYZE " + q)
        fused = dict(zip(out.columns["operator"],
                         out.columns["fused"]))
        assert fused["filter"] == fused["aggregate"] == "g1"
        # the group's wall time rolls up on its FIRST member's row;
        # the later member just unpacks the already-fetched result
        times = dict(zip(out.columns["operator"],
                         out.columns["time_ms"].tolist()))
        assert times["aggregate"] <= times["filter"]
    finally:
        _config.set_default_config(prev)
        session.drop_table("eaf")


def test_explain_analyze_sharded_columns(session, mc):
    """Queries that hit the sharded path (a mesh bound via use_mesh +
    the distributed chip-exchange overlay) surface per-shard skew and
    all_to_all bytes on the operator row that moved them."""
    import jax
    from mosaic_tpu.obs import metrics
    session.create_table("shpairs", {"ga": _zones(), "gb": _zones()})
    was = metrics.enabled
    metrics.enable()
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))
    try:
        mc.use_mesh(mesh)
        out = session.sql(
            "EXPLAIN ANALYZE SELECT grid_intersects_sharded(ga, gb, 2) "
            "AS hit FROM shpairs")
        by_op = {out.columns["operator"][i]: i for i in range(len(out))}
        proj, scan = by_op["project"], by_op["scan"]
        # the projection drove the exchange; the scan moved nothing
        assert out.columns["all_to_all_bytes"][proj] > 0
        assert out.columns["shard_skew"][proj] >= 1.0
        assert out.columns["all_to_all_bytes"][scan] == 0
        assert out.columns["shard_skew"][scan] == 0.0
        # per-device wall-time attribution (obs.devicemon): the
        # overlay charged its wall clock to mesh devices during the
        # projection, so that row's device_ms cell names devices;
        # the scan attributed nothing
        assert out.columns["device_ms"][proj] != "-"
        import re as _re
        assert _re.search(r"cpu:\d+=\d", out.columns["device_ms"][proj])
        assert out.columns["device_ms"][scan] == "-"
        # and the distributed operator still computes the right answer
        res = session.sql("SELECT grid_intersects_sharded(ga, gb, 2) "
                          "AS hit FROM shpairs")
        assert np.asarray(res.columns["hit"]).tolist() == [True, True]
    finally:
        mc.use_mesh(None)
        if not was:
            metrics.disable()


def test_concurrent_queries_interleave_with_disjoint_accounting(mc):
    """Satellite of the accounting plane: two sessions querying from
    two threads get disjoint query tickets, disjoint per-trace span
    profiles, and per-principal meter splits that add up."""
    import threading

    from mosaic_tpu.obs import metrics, tracer
    from mosaic_tpu.obs.accounting import audit, meter
    audit.reset(); meter.reset()
    metrics.reset(); metrics.enable(); tracer.enable()
    barrier = threading.Barrier(2)

    def worker(principal, n):
        s = SQLSession(mc)
        s.principal = principal
        s.create_table("t", {"v": np.arange(float(n))})
        barrier.wait()
        for _ in range(4):
            s.sql("SELECT v FROM t WHERE v < 1e9")

    try:
        ts = [threading.Thread(target=worker, args=("alice", 30)),
              threading.Thread(target=worker, args=("bob", 70))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
            assert not t.is_alive()
        recs = audit.records()
        assert len(recs) == 8
        assert len({r["query_id"] for r in recs}) == 8
        assert len({r["trace"] for r in recs}) == 8
        # every query's spans landed under its OWN trace: the span
        # profile for each audited trace exists and none is shared
        traces = tracer.report()["traces"]
        for r in recs:
            assert r["trace"] in traces
            assert traces[r["trace"]]["spans"]
        rep = meter.report()
        assert rep["alice"]["queries"] == 4
        assert rep["bob"]["queries"] == 4
        assert rep["alice"]["rows_out"] == 4 * 30
        assert rep["bob"]["rows_out"] == 4 * 70
        assert rep["alice"]["outcomes"] == {"ok": 4}
    finally:
        tracer.disable(); tracer.reset()
        metrics.disable(); metrics.reset()
        audit.reset(); meter.reset()
