"""Out-of-core chip store: round-trip parity, pruning, torn shards.

The store's contract has three legs and each gets direct coverage:

* **bit parity** — writer→reader returns exactly the source values in
  store order (a pure function of data and grid, not of ingest block
  boundaries), and the store-fed sharded join matches the in-memory
  sharded path bit for bit;
* **pruning is conservative** — fuzzing random query boxes, a
  bbox-pruned read never loses a row the full scan's filter keeps,
  and a pruned partition provably stages zero bytes (the join's
  per-partition ledger reconciles against ``pipeline/h2d_bytes``);
* **degrade, not die** — torn/truncated shards under the chaos
  fixtures follow the codec ``on_error`` convention (raise a located
  CodecError / drop the torn tail / zero-fill), with the
  ``store/shards_torn`` counter and ``store_shard_torn`` event.
"""

import os

import jax
import numpy as np
import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.resilience.ingest import CodecError
from mosaic_tpu.sql.parser import parse
from mosaic_tpu.store import (ChipStore, StoreWriter, bbox_from_where,
                              grid_cells, write_store,
                              write_store_from_chunks)

RES = 4096


def _pts(n, seed=0, lo=(-74.3, 40.5), hi=(-73.7, 40.95)):
    rng = np.random.default_rng(seed)
    return np.column_stack([rng.uniform(lo[0], hi[0], n),
                            rng.uniform(lo[1], hi[1], n)])


def _store_order(pts, res=RES):
    return np.argsort(grid_cells(pts[:, 0], pts[:, 1], res),
                      kind="stable")


# ------------------------------------------------------- round trip

def test_round_trip_bit_parity(tmp_path):
    pts = _pts(20_000, seed=1)
    w = np.random.default_rng(2).standard_normal(20_000)
    tag = np.arange(20_000, dtype=np.int64)
    man = write_store(str(tmp_path), pts, columns={"w": w, "tag": tag},
                      grid_res=RES, shard_rows=2048)
    assert man.total_rows == 20_000
    assert sum(p.rows for p in man.partitions) == 20_000
    st = ChipStore(str(tmp_path))
    cols = st.read_columns()
    order = _store_order(pts)
    assert np.array_equal(cols["x"], pts[order, 0])
    assert np.array_equal(cols["y"], pts[order, 1])
    assert np.array_equal(cols["w"], w[order])
    assert np.array_equal(cols["tag"], tag[order])
    assert cols["tag"].dtype == np.int64      # schema survives


def test_multi_block_ingest_matches_one_shot(tmp_path):
    """Store order is a function of (data, grid) only — block
    boundaries during ingest are invisible in the read-back."""
    pts = _pts(9_000, seed=3)
    one = tmp_path / "one"
    many = tmp_path / "many"
    write_store(str(one), pts, grid_res=RES, shard_rows=1024)
    write_store_from_chunks(
        str(many), (pts[i:i + 1_000] for i in range(0, 9_000, 1_000)),
        grid_res=RES, shard_rows=1024)
    a = ChipStore(str(one)).read_columns()
    b = ChipStore(str(many)).read_columns()
    assert np.array_equal(a["x"], b["x"])
    assert np.array_equal(a["y"], b["y"])


def test_iter_chunks_streams_everything_in_store_order(tmp_path):
    pts = _pts(10_000, seed=4)
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=512)
    st = ChipStore(str(tmp_path))
    chunks = list(st.iter_chunks(chunk_rows=2048))
    got = np.concatenate([c.points for c in chunks])
    order = _store_order(pts)
    assert np.array_equal(got, pts[order])
    # full chunks are exactly the pow2 target; spans cover each chunk
    assert all(c.rows == 2048 for c in chunks[:-1])
    for c in chunks:
        assert sum(r for _, r in c.parts) == c.rows
    # offsets are the running row count
    assert [c.offset for c in chunks] == \
        list(np.cumsum([0] + [c.rows for c in chunks[:-1]]))


def test_unfinalized_store_is_invisible(tmp_path):
    """Manifest-last atomicity: a crash before finalize leaves no
    readable store."""
    w = StoreWriter(str(tmp_path), grid_res=RES)
    w.append(_pts(500, seed=5))
    with pytest.raises(CodecError, match="manifest"):
        ChipStore(str(tmp_path))


# ---------------------------------------------------------- pruning

def test_bbox_pruning_never_drops_a_matching_row_fuzz(tmp_path):
    pts = _pts(30_000, seed=6)
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=4096)
    st = ChipStore(str(tmp_path))
    rng = np.random.default_rng(7)
    pruned_any = False
    for _ in range(25):
        x0, x1 = np.sort(rng.uniform(-74.35, -73.65, 2))
        y0, y1 = np.sort(rng.uniform(40.45, 41.0, 2))
        bbox = (x0, y0, x1, y1)
        scanned = st.prune(bbox, record=False)
        pruned_any |= len(scanned) < len(st.partitions)
        cols = st.read_columns(bbox=bbox)
        inside = ((cols["x"] >= x0) & (cols["x"] <= x1) &
                  (cols["y"] >= y0) & (cols["y"] <= y1))
        want = ((pts[:, 0] >= x0) & (pts[:, 0] <= x1) &
                (pts[:, 1] >= y0) & (pts[:, 1] <= y1))
        # the scanned superset holds EVERY matching row
        assert int(inside.sum()) == int(want.sum())
    assert pruned_any                 # the fuzz exercised real pruning


def test_prune_counts_metrics(tmp_path):
    write_store(str(tmp_path), _pts(5_000, seed=8), grid_res=RES)
    st = ChipStore(str(tmp_path))
    metrics.enable()
    p0 = metrics.counter_value("store/partitions_pruned")
    s0 = metrics.counter_value("store/partitions_scanned")
    scanned = st.prune((-74.0, 40.6, -73.9, 40.7))
    assert metrics.counter_value("store/partitions_scanned") - s0 == \
        len(scanned)
    assert metrics.counter_value("store/partitions_pruned") - p0 == \
        len(st.partitions) - len(scanned) > 0


def test_bbox_from_where_extraction():
    def bb(sql):
        return bbox_from_where(parse(sql).where, "x", "y")

    assert bb("SELECT * FROM t WHERE x >= 1 AND x < 2 "
              "AND y > 3 AND y <= 4") == (1.0, 3.0, 2.0, 4.0)
    # literal-first comparisons flip; equality pins both sides
    assert bb("SELECT * FROM t WHERE 1 <= x AND y = -2") == \
        (1.0, -2.0, float("inf"), -2.0)
    # OR at the top level confines nothing (conservative: full scan)
    assert bb("SELECT * FROM t WHERE x > 1 OR y > 2") is None
    # non-point columns and column-vs-column comparisons are ignored
    assert bb("SELECT * FROM t WHERE w > 9") is None
    assert bb("SELECT * FROM t WHERE x > y") is None
    assert bb("SELECT * FROM t") is None


# ------------------------------------------------- SQL integration

@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


def test_sql_store_scan_parity_and_explain(tmp_path, mc):
    from mosaic_tpu.sql.engine import SQLSession
    pts = _pts(8_000, seed=9)
    w = np.random.default_rng(10).standard_normal(8_000)
    write_store(str(tmp_path), pts, columns={"w": w}, grid_res=RES,
                shard_rows=2048)
    s = SQLSession(mc)
    s.register_store("chips", str(tmp_path))
    q = ("FROM chips WHERE x >= -74.0 AND x <= -73.9 "
         "AND y >= 40.6 AND y <= 40.7")
    out = s.sql("SELECT x, y, w " + q)
    # parity vs the same predicate over an in-memory table (row order
    # differs — store order vs ingest order — so compare as sets)
    s.create_table("mem", {"x": pts[:, 0], "y": pts[:, 1], "w": w})
    ref = s.sql("SELECT x, y, w " + q.replace("chips", "mem"))
    assert len(out) == len(ref) > 0
    assert np.array_equal(np.sort(np.asarray(out.column("w"))),
                          np.sort(np.asarray(ref.column("w"))))
    # EXPLAIN shows pruning as scanned/total without reading data
    plan = s.sql("EXPLAIN SELECT x " + q)
    ops = list(plan.column("operator"))
    parts = plan.column("partitions")[ops.index("scan")]
    scanned, total = map(int, parts.split("/"))
    assert 0 < scanned < total
    # non-store rows show "-"
    assert plan.column("partitions")[ops.index("filter")] == "-"


# ------------------------------------------- store-fed sharded join

def _mesh4():
    return jax.sharding.Mesh(np.array(jax.devices()[:4]), ("data",))


@pytest.fixture(scope="module")
def workload():
    from mosaic_tpu.bench.workloads import build_workload
    from mosaic_tpu.parallel.pip_join import build_pip_index
    polys, grid, res = build_workload(n_side=6, res_cells=64)
    idx = build_pip_index(polys, res, grid)
    return polys, grid, res, idx


def test_store_fed_join_bit_parity_vs_in_memory(tmp_path, workload):
    from mosaic_tpu.parallel.pip_join import (
        make_sharded_streamed_pip_join, make_store_sharded_pip_join)
    polys, grid, res, idx = workload
    pts = _pts(20_000, seed=11)
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=2048)
    st = ChipStore(str(tmp_path))
    mesh = _mesh4()
    sj = make_store_sharded_pip_join(st, idx, grid, mesh, polys=polys,
                                     chunk=4096, refresh=2)
    zone_s, rc_s = sj()
    cols = st.read_columns(cols=st.point_cols)
    store_pts = np.column_stack([cols["x"], cols["y"]])
    mj = make_sharded_streamed_pip_join(idx, grid, mesh, polys=polys,
                                        chunk=4096, refresh=2)
    zone_m, rc_m = mj(store_pts)
    assert np.array_equal(zone_s, zone_m)
    assert rc_s == rc_m
    # the placement pass observed every chunk
    assert sj.rebalancer.observations == len(zone_s) // 4096 + \
        (1 if len(zone_s) % 4096 else 0)


def test_store_fed_join_pruned_partitions_never_staged(tmp_path,
                                                       workload):
    """The acceptance invariant: a bbox query stages ZERO bytes for
    pruned partitions.  The join's per-partition ledger covers only
    scanned cells AND reconciles byte-for-byte with the pipeline's
    ``pipeline/h2d_bytes`` staging counter, so no staged byte can hide
    under a pruned cell; the memwatch ledger drains to zero live
    bytes (nothing stayed resident)."""
    from mosaic_tpu.obs.memwatch import memwatch
    from mosaic_tpu.parallel.pip_join import make_store_sharded_pip_join
    polys, grid, res, idx = workload
    pts = _pts(20_000, seed=12)
    write_store(str(tmp_path), pts, grid_res=RES, shard_rows=2048)
    st = ChipStore(str(tmp_path))
    bbox = (-74.05, 40.6, -73.9, 40.75)
    scanned = {p.cell for p in st.prune(bbox, record=False)}
    pruned = {p.cell for p in st.partitions} - scanned
    assert scanned and pruned          # non-vacuous on both sides
    metrics.enable()
    sj = make_store_sharded_pip_join(st, idx, grid, _mesh4(),
                                     polys=polys, chunk=2048)
    h2d0 = metrics.counter_value("pipeline/h2d_bytes")
    zone, _ = sj(bbox=bbox)
    h2d = metrics.counter_value("pipeline/h2d_bytes") - h2d0
    ledger = sj.staged_bytes_by_partition
    assert set(ledger) <= scanned
    assert not (set(ledger) & pruned)
    assert sum(ledger.values()) == int(h2d) > 0
    assert len(zone) == sum(p.rows for p in st.prune(bbox,
                                                     record=False))
    if memwatch.enabled:
        assert memwatch.live_bytes() == 0


# --------------------------------------------------- chaos / faults

def test_torn_shard_skip_drops_only_torn_tail(tmp_path, fault_plan):
    pts = _pts(4_000, seed=13)
    write_store(str(tmp_path), pts, grid_res=64, shard_rows=512)
    clean = ChipStore(str(tmp_path), on_error="raise")
    full = clean.read_columns()
    metrics.enable()
    recorder.enable()
    t0 = metrics.counter_value("store/shards_torn")
    fault_plan("seed=21;site=store.shard,fails=1,mode=truncate")
    st = ChipStore(str(tmp_path), on_error="skip")
    cols = st.read_columns()
    lost = len(full["x"]) - len(cols["x"])
    assert 0 < lost < len(full["x"])   # torn tail dropped, rest intact
    assert metrics.counter_value("store/shards_torn") - t0 >= 1
    evs = recorder.events("store_shard_torn")
    assert evs and evs[-1]["mode"] == "skip"
    # surviving values are a sub-multiset of the clean read
    vals, counts = np.unique(cols["x"], return_counts=True)
    fvals, fcounts = np.unique(full["x"], return_counts=True)
    idx_in_full = np.searchsorted(fvals, vals)
    assert np.array_equal(fvals[idx_in_full], vals)
    assert np.all(counts <= fcounts[idx_in_full])


def test_torn_shard_raise_and_null_modes(tmp_path, fault_plan):
    pts = _pts(2_000, seed=14)
    write_store(str(tmp_path), pts, grid_res=64, shard_rows=256)
    clean = ChipStore(str(tmp_path), on_error="raise")
    n_full = len(clean.read_columns()["x"])
    fault_plan("seed=22;site=store.shard,fails=1,mode=truncate")
    with pytest.raises(CodecError, match="torn shard"):
        ChipStore(str(tmp_path), on_error="raise").read_columns()
    fault_plan("seed=22;site=store.shard,fails=1,mode=truncate")
    cols = ChipStore(str(tmp_path), on_error="null").read_columns()
    # null mode keeps the row count, zero-filling the torn tail
    assert len(cols["x"]) == n_full


def test_store_read_fault_surfaces(tmp_path, fault_plan):
    write_store(str(tmp_path), _pts(500, seed=15), grid_res=64)
    from mosaic_tpu.resilience.faults import InjectedFault
    fault_plan("seed=23;site=store.read,fails=1")
    with pytest.raises(InjectedFault):
        ChipStore(str(tmp_path))


def test_store_write_fault_leaves_no_store(tmp_path, fault_plan):
    """An injected crash during ingest must leave the target
    unreadable (manifest-last atomicity), not half-written."""
    from mosaic_tpu.resilience.faults import InjectedFault
    fault_plan("seed=24;site=store.write,fails=1")
    w = StoreWriter(str(tmp_path), grid_res=64)
    with pytest.raises(InjectedFault):
        w.append(_pts(500, seed=16))
    with pytest.raises(CodecError, match="manifest"):
        ChipStore(str(tmp_path))


# ----------------------------------------------------------- config

def test_store_conf_keys_registered():
    cfg = _config.MosaicConfig()
    cfg = _config.apply_conf(cfg, "mosaic.store.dir", "/tmp/s")
    cfg = _config.apply_conf(cfg, "mosaic.store.grid.res", "2048")
    cfg = _config.apply_conf(cfg, "mosaic.store.shard.rows", "65536")
    cfg = _config.apply_conf(cfg, "mosaic.store.mmap", "false")
    assert cfg.store_dir == "/tmp/s"
    assert cfg.store_grid_res == 2048
    assert cfg.store_shard_rows == 65536
    assert cfg.store_mmap is False
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(cfg, "mosaic.store.grid.res", "0")
    with pytest.raises(_config.ConfigError):
        _config.apply_conf(cfg, "mosaic.store.mmap", "maybe")
