"""Serving-fleet supervisor (``serve/supervisor.py``) on fake workers.

Every test here swaps ``worker_cmd`` for a tiny jax-free stub that
writes its ready file and sleeps, so the supervisor's control plane —
spawn/ready bookkeeping, crash detection + backoff respawn, the
crash-loop circuit breaker, SIGTERM drain with the bounded hard-kill
path, and the ``serve.spawn`` fault site — is exercised in
milliseconds.  The end-to-end fleet (real ``QueryServer`` workers,
kill drill, warm-cache respawn) runs in bench.py's fleet stage and
the fleet-chaos CI lane.
"""

import json
import os
import signal
import socket
import sys
import textwrap
import time

import pytest

from mosaic_tpu import config as _config
from mosaic_tpu.obs import metrics
from mosaic_tpu.obs.recorder import recorder
from mosaic_tpu.resilience import faults
from mosaic_tpu.serve.supervisor import (SCOREBOARD_FILE,
                                         SUPERVISOR_FILE, ServeFleet)

pytestmark = pytest.mark.skipif(os.name != "posix",
                                reason="fleet supervisor is POSIX")

#: a worker that comes up instantly: ready file, then sleep; exits 0
#: on SIGTERM like a draining QueryServer would
_STUB = textwrap.dedent("""
    import json, os, signal, sys, time
    d = os.environ["MOSAIC_FLEET_DIR"]
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))
    with open(os.path.join(d, "ready-%d.json" % os.getpid()), "w") as f:
        json.dump({"pid": os.getpid()}, f)
    time.sleep(120)
""")

#: a worker that refuses to drain: SIGTERM is ignored
_STUB_DEAF = _STUB.replace(
    "lambda *a: sys.exit(0)", "signal.SIG_IGN")

#: a worker that dies before ever becoming ready
_STUB_DOA = "import sys; sys.exit(3)"


def _stub_cmd(src=_STUB):
    return [sys.executable, "-c", src]


@pytest.fixture
def fleet_env():
    prev = _config.default_config()
    metrics.reset()
    metrics.enable()
    recorder.reset()
    recorder.enable()
    yield
    faults.disarm()
    _config.set_default_config(prev)
    metrics.disable()
    metrics.reset()
    recorder.reset()


def _conf(**keys):
    cfg = _config.default_config()
    for k, v in keys.items():
        cfg = _config.apply_conf(cfg, k.replace("_", "."), str(v))
    _config.set_default_config(cfg)


def _counter(name):
    return metrics.report()["counters"].get(name, 0)


def _events(name):
    return recorder.events(name)


def _fleet(tmp_path, workers=2, stub=_STUB, **kw):
    return ServeFleet(workers=workers, port=0,
                      fleet_dir=str(tmp_path / "fleet"),
                      worker_cmd=_stub_cmd(stub), **kw)


# --------------------------------------------------------- lifecycle

def test_start_ready_status_stop(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0)    # tests drive tick()
    fleet = _fleet(tmp_path, workers=2)
    with fleet:
        assert len(fleet.worker_pids()) == 2
        st = fleet.status()
        assert st["live"] == 2 and st["degraded"] == 0
        assert all(w["ready"] for w in st["workers"])
        assert _counter("serve/worker_spawns") == 2
        assert len(_events("fleet_worker_spawn")) == 2
        # the fleet dir carries the whole control plane
        names = os.listdir(fleet.fleet_dir)
        assert SCOREBOARD_FILE in names and SUPERVISOR_FILE in names
    # clean drain: stubs exit on SIGTERM, nothing was forced
    assert _counter("serve/drain_forced") == 0
    assert fleet.worker_pids() == []
    disk = json.load(open(os.path.join(fleet.fleet_dir,
                                       SUPERVISOR_FILE)))
    assert disk["stopping"] is True and disk["live"] == 0


def test_no_worker_ready_raises(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0)
    fleet = _fleet(tmp_path, workers=2, stub=_STUB_DOA)
    with pytest.raises(RuntimeError, match="no fleet worker"):
        fleet.start(ready_timeout_s=10)


def test_parent_socket_fallback_mode(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0)
    with _fleet(tmp_path, workers=1,
                force_parent_socket=True) as fleet:
        assert fleet.mode == "parent_socket"
        # the parent holds a real listener: connects are accepted
        # (queued) even though the stub never calls accept()
        with socket.create_connection(("127.0.0.1", fleet.port),
                                      timeout=5):
            pass
    # stop() closed it
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", fleet.port),
                                 timeout=0.5)


# ------------------------------------------------- crash -> respawn

def test_crash_respawns_through_backoff(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0)
    with _fleet(tmp_path, workers=2) as fleet:
        victim = fleet.worker_pids()[0]
        os.kill(victim, signal.SIGKILL)
        deadline = time.time() + 1.0      # let the kernel reap it
        while time.time() < deadline:
            fleet.tick()
            if _counter("serve/worker_crashes"):
                break
            time.sleep(0.02)
        assert _counter("serve/worker_crashes") == 1
        assert len(_events("fleet_worker_exit")) == 1
        # parked until the backoff is due; a far-future tick respawns
        fleet.tick(now=time.time() + 60.0)
        pids = fleet.worker_pids()
        assert len(pids) == 2 and victim not in pids
        assert _counter("serve/worker_respawns") == 1
        st = fleet.status()
        assert st["degraded"] == 0
        assert [w for w in st["workers"]
                if w["restarts"] == 1] != []


def test_breaker_parks_slot_and_fleet_survives(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0,
          mosaic_serve_fleet_restart_max=1,
          mosaic_serve_fleet_restart_window_ms=600_000)
    with _fleet(tmp_path, workers=2) as fleet:
        for round_ in range(2):           # crash 1 respawns; 2 trips
            victim = fleet.status()["workers"][0]["pid"]
            os.kill(victim, signal.SIGKILL)
            deadline = time.time() + 1.0
            while time.time() < deadline:
                fleet.tick(now=time.time() + 60.0 * (round_ + 1))
                ws = fleet.status()["workers"][0]
                if ws["degraded"] or (ws["alive"] and
                                      ws["pid"] != victim):
                    break
                time.sleep(0.02)
        st = fleet.status()
        assert st["degraded"] == 1
        assert st["live"] == 1            # degraded = run at N-1
        assert _counter("serve/fleet_degraded") == 1
        evs = _events("fleet_degraded")
        assert len(evs) == 1 and evs[0]["index"] == 0
        # the breaker holds: more ticks never resurrect the slot
        fleet.tick(now=time.time() + 600.0)
        assert fleet.status()["live"] == 1
        assert _counter("serve/fleet_degraded") == 1


# ------------------------------------------------------- drain paths

def test_sigterm_ignoring_worker_is_force_killed(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0,
          mosaic_serve_drain_ms=200)
    fleet = _fleet(tmp_path, workers=2, stub=_STUB_DEAF)
    fleet.start()
    pids = fleet.worker_pids()
    t0 = time.time()
    fleet.stop(drain=True)
    assert _counter("serve/drain_forced") == 2
    assert time.time() - t0 < 10.0        # bounded, not a hang
    for pid in pids:
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)


def test_signal_handler_drains_fleet(tmp_path, fleet_env):
    _conf(mosaic_serve_fleet_health_ms=0)
    fleet = _fleet(tmp_path, workers=1)
    fleet.start()
    fleet.install_signal_handlers()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert fleet.wait(timeout=10.0)
        deadline = time.time() + 5.0
        while fleet.worker_pids() and time.time() < deadline:
            time.sleep(0.05)
        assert fleet.worker_pids() == []
        assert _counter("serve/drain_forced") == 0
    finally:
        fleet.stop()


# ------------------------------------------------------ spawn chaos

def test_spawn_fault_is_retried(tmp_path, fleet_env, fault_plan):
    _conf(mosaic_serve_fleet_health_ms=0)
    fault_plan("seed=5;site=serve.spawn,fails=1,error=OSError")
    with _fleet(tmp_path, workers=2) as fleet:
        # first exec raised, SERVE_SPAWN_RETRY recovered it
        assert len(fleet.worker_pids()) == 2
        assert _counter("retry/recovered/serve.spawn") == 1
        assert _counter("serve/worker_spawns") == 2


def test_spawn_fault_exhaustion_counts_failure(tmp_path, fleet_env,
                                               fault_plan):
    """Every attempt for one slot fails: the slot books a spawn
    failure and the OTHER worker still comes up — degrade, not die."""
    _conf(mosaic_serve_fleet_health_ms=0)
    fault_plan("seed=5;site=serve.spawn,fails=3,error=OSError")
    fleet = _fleet(tmp_path, workers=2)
    with fleet:
        assert _counter("serve/worker_spawn_failures") == 1
        assert _counter("retry/giveups/serve.spawn") == 1
        assert len(fleet.worker_pids()) == 1


# -------------------------------------------------------- reap tick

def test_tick_reaps_dead_scoreboard_claims(tmp_path, fleet_env):
    from mosaic_tpu.serve.scoreboard import Scoreboard
    _conf(mosaic_serve_fleet_health_ms=0,
          mosaic_serve_fleet_reap_ms=0)     # reap on every tick
    with _fleet(tmp_path, workers=1) as fleet:
        sb_path = os.path.join(fleet.fleet_dir, SCOREBOARD_FILE)
        victim = fleet.worker_pids()[0]
        with Scoreboard(sb_path) as mine:
            # plant a claim owned by the worker, then kill the worker
            tok, deny = mine.admit("t", 0, 0)
            assert deny is None
            import struct as _struct
            from mosaic_tpu.serve import scoreboard as _sbmod
            off = _sbmod._HEADER_SIZE + tok.index * _sbmod._SLOT_SIZE
            with open(sb_path, "r+b") as f:
                raw = bytearray(_sbmod._SLOT.pack(
                    tok.seq, 1, victim, time.time(),
                    b"t".ljust(44, b"\0")))
                f.seek(off)
                f.write(bytes(raw))
            os.kill(victim, signal.SIGKILL)
            deadline = time.time() + 2.0
            while time.time() < deadline:
                fleet.tick()
                if mine.counts("t")["concurrency"] == 0:
                    break
                time.sleep(0.02)
            assert mine.counts("t")["concurrency"] == 0
