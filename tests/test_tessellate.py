"""Tessellation engine tests against the CUSTOM rectangular grid.

Mirrors the reference's trick of exercising the engine with
CustomIndexSystem(GridConf(-180,180,-90,90,2,360,180))
(test/MosaicSpatialQueryTest.scala:21-26) so correctness is checked with
exactly computable expected cells.
"""

import numpy as np
import pytest

from mosaic_tpu import GeometryArray, get_index_system, read_wkt
from mosaic_tpu.core.index.custom import CustomIndexSystem, GridConf
from mosaic_tpu.core.tessellate import polyfill, tessellate


@pytest.fixture(scope="module")
def grid():
    # unit grid: res 0 cells are 1x1 over [0,16)²; res 1 → 0.5; splits=2
    return CustomIndexSystem(GridConf(0, 16, 0, 16, 2, 1.0, 1.0))


def test_factory_parses_custom():
    g = get_index_system("CUSTOM(-180,180,-90,90,2,360,180)")
    assert isinstance(g, CustomIndexSystem)
    assert g.conf.root_cells_x == 1
    g2 = get_index_system("CUSTOM(0, 16, 0, 16, 2, 1.0, 1.0, 27700)")
    assert g2.crs_id == 27700


def test_point_to_cell_roundtrip(grid):
    xy = np.array([[0.5, 0.5], [3.2, 7.9], [15.99, 15.01]])
    cells = grid.point_to_cell(xy, 0)
    centers = grid.cell_center(cells)
    assert np.allclose(centers, [[0.5, 0.5], [3.5, 7.5], [15.5, 15.5]])
    assert np.array_equal(grid.point_to_cell(centers, 0), cells)
    assert np.all(grid.resolution_of(cells) == 0)


def test_cell_boundary_ccw(grid):
    cells = grid.point_to_cell(np.array([[2.5, 3.5]]), 0)
    verts, counts = grid.cell_boundary(cells)
    assert counts[0] == 4
    x, y = verts[0, :, 0], verts[0, :, 1]
    area = 0.5 * np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y)
    assert area == pytest.approx(1.0)  # positive => CCW


def test_k_ring_loop(grid):
    cells = grid.point_to_cell(np.array([[5.5, 5.5]]), 0)
    ring = grid.k_ring(cells, 1)
    assert ring.shape == (1, 9)
    assert np.all(ring >= 0)
    loop = grid.k_loop(cells, 1)
    valid = loop[loop >= 0]
    assert len(valid) == 8
    assert int(cells[0]) not in valid.tolist()
    # edge of grid: some neighbors invalid
    corner = grid.point_to_cell(np.array([[0.5, 0.5]]), 0)
    ring = grid.k_ring(corner, 1)
    assert (ring >= 0).sum() == 4


def test_polyfill_square(grid):
    # polygon covering cells (1..3, 1..3) centers: 2x2 cells fully, centers
    # of cells with center in [1.2, 3.2]x[1.2, 3.2]
    arr = read_wkt(["POLYGON ((1.2 1.2, 3.2 1.2, 3.2 3.2, 1.2 3.2, 1.2 1.2))"])
    cells = polyfill(arr, 0, grid)[0]
    centers = grid.cell_center(cells)
    # centers inside: x,y in {1.5, 2.5} -> 4 cells... also 3.5>3.2 no
    assert len(cells) == 4
    assert np.all((centers > 1.2) & (centers < 3.2))


def test_tessellate_core_border(grid):
    arr = read_wkt(["POLYGON ((0.5 0.5, 4.5 0.5, 4.5 4.5, 0.5 4.5, 0.5 0.5))"])
    chips = tessellate(arr, 0, grid)
    # cells 1..3 x 1..3 are fully inside => 9 core; ring of partial cells
    # from 0..4 x 0..4 => 25 touching total, 16 border
    assert len(chips) == 25
    assert chips.is_core.sum() == 9
    border = ~chips.is_core
    assert border.sum() == 16
    # border chip areas: corners 0.25, edges 0.5
    from mosaic_tpu.core.geometry.padded import build_edges
    from mosaic_tpu.core.geometry import measures
    e = build_edges(chips.geoms, dtype=np.float64)
    areas = np.asarray(measures.area(e))
    assert np.allclose(np.sort(areas[border]),
                       np.sort([0.25] * 4 + [0.5] * 12))
    assert np.allclose(areas[chips.is_core], 1.0)
    # total chip area = polygon area
    assert areas.sum() == pytest.approx(16.0)


def test_tessellate_with_hole(grid):
    arr = read_wkt([
        "POLYGON ((0.5 0.5, 7.5 0.5, 7.5 7.5, 0.5 7.5, 0.5 0.5),"
        " (2.5 2.5, 5.5 2.5, 5.5 5.5, 2.5 5.5, 2.5 2.5))"])
    chips = tessellate(arr, 0, grid)
    from mosaic_tpu.core.geometry.padded import build_edges
    from mosaic_tpu.core.geometry import measures
    e = build_edges(chips.geoms, dtype=np.float64)
    areas = np.asarray(measures.area(e))
    assert areas.sum() == pytest.approx(49.0 - 9.0)
    # cells fully inside the hole must not appear
    hole_cells = grid.point_to_cell(np.array([[4.0, 4.0]]), 0)
    assert int(hole_cells[0]) not in chips.cell_id.tolist()


def test_tessellate_point_and_line(grid):
    arr = read_wkt(["POINT (2.2 3.3)", "LINESTRING (0.5 0.5, 3.5 0.5)"])
    chips = tessellate(arr, 0, grid)
    pt_chips = chips.cell_id[chips.geom_id == 0]
    assert len(pt_chips) == 1
    assert pt_chips[0] == grid.point_to_cell(np.array([[2.2, 3.3]]), 0)[0]
    line_chips = chips.cell_id[chips.geom_id == 1]
    assert len(line_chips) == 4  # passes through x cells 0..3 at y row 0
    assert not chips.is_core.any()


def test_tessellate_chip_cover_parity(grid):
    """Every point sampled inside the polygon must fall in exactly one
    chip's cell, and the chip must contain it (the PIP-join invariant)."""
    arr = read_wkt(["POLYGON ((1.3 1.7, 6.8 2.1, 5.9 6.3, 2.2 5.8, 1.3 1.7))"])
    chips = tessellate(arr, 0, grid)
    rng = np.random.default_rng(0)
    pts = rng.uniform(0, 8, size=(500, 2))
    from mosaic_tpu.core.tessellate import _pip, _poly_edges
    edges = _poly_edges(arr, 0)
    truth = _pip(pts, edges)
    # join: cell of point -> chips
    cells = grid.point_to_cell(pts, 0)
    cell_to_chips = {}
    for i, c in enumerate(chips.cell_id):
        cell_to_chips.setdefault(int(c), []).append(i)
    joined = np.zeros(len(pts), dtype=bool)
    for k, c in enumerate(cells):
        for ci in cell_to_chips.get(int(c), []):
            if chips.is_core[ci]:
                joined[k] = True
            else:
                chip_edges = _poly_edges(chips.geoms, ci)
                if _pip(pts[k:k + 1], chip_edges)[0]:
                    joined[k] = True
    assert np.array_equal(joined, truth)


def test_resolution_1(grid):
    arr = read_wkt(["POLYGON ((1.2 1.2, 3.2 1.2, 3.2 3.2, 1.2 3.2, 1.2 1.2))"])
    chips0 = tessellate(arr, 0, grid)
    chips1 = tessellate(arr, 1, grid)
    from mosaic_tpu.core.geometry.padded import build_edges
    from mosaic_tpu.core.geometry import measures
    a0 = float(np.asarray(measures.area(
        build_edges(chips0.geoms, dtype=np.float64))).sum())
    a1 = float(np.asarray(measures.area(
        build_edges(chips1.geoms, dtype=np.float64))).sum())
    assert a0 == pytest.approx(4.0)
    assert a1 == pytest.approx(4.0)
    assert chips1.is_core.sum() > chips0.is_core.sum()


def test_cell_area(grid):
    cells = grid.point_to_cell(np.array([[5.5, 5.5]]), 0)
    assert grid.cell_area(cells)[0] == pytest.approx(1.0)
    cells1 = grid.point_to_cell(np.array([[5.5, 5.5]]), 2)
    assert grid.cell_area(cells1)[0] == pytest.approx(1 / 16)


def test_format_parse_ids(grid):
    cells = grid.point_to_cell(np.array([[5.5, 5.5], [1.1, 2.2]]), 1)
    s = grid.format_cell_id(cells)
    back = grid.parse_cell_id(s)
    assert np.array_equal(back, cells)


def test_sample_kernel_candidates_match_host():
    """The jitted candidate-sampling kernel must yield the same chip
    rows as the exact host path (device-vs-host parity for the round-4
    batched tessellation; the sampling path only needs sub-inradius
    accuracy, but the RESULTING chips must be identical because
    classification is exact either way)."""
    import jax
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.core.tessellate import tessellate
    grid_dev = get_index_system("H3")
    grid_host = get_index_system("H3")
    # force the host path on one instance
    grid_host._point_to_cell_sample = \
        lambda xy, res: grid_host.point_to_cell(xy, res)
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    rng = np.random.default_rng(4)
    b = GeometryBuilder()
    for _ in range(25):
        cx, cy = rng.uniform(-74.2, -73.8), rng.uniform(40.6, 40.9)
        ang = 2 * np.pi * (np.arange(7) +
                           rng.uniform(-0.3, 0.3, 7)) / 7
        rad = rng.uniform(0.004, 0.02, 7)
        ring = np.stack([cx + rad * np.cos(ang),
                         cy + rad * np.sin(ang)], -1)
        b.add_polygon(np.vstack([ring, ring[:1]]))
    polys = b.finish()
    a = tessellate(polys, 8, grid_dev, keep_core_geom=True)
    c = tessellate(polys, 8, grid_host, keep_core_geom=True)
    assert np.array_equal(a.cell_id, c.cell_id)
    assert np.array_equal(a.is_core, c.is_core)
    assert np.array_equal(a.geom_id, c.geom_id)
    np.testing.assert_array_equal(a.geoms.coords, c.geoms.coords)


def test_pentagon_core_ring_closed():
    """keep_core_geom=True core chips must emit CLOSED rings for
    pentagon cells too (round-4 review: padded boundary rows repeat the
    LAST vertex, so the bulk wrap put a duplicate there instead of the
    first vertex)."""
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    from mosaic_tpu.core.index.h3.tables import tables
    grid = get_index_system("H3")
    t = tables()
    # a box around a pentagon center catches pentagon core cells
    lat, lng = np.degrees(t.center_geo[4])
    b = GeometryBuilder()
    ring = np.array([[lng - 1.2, lat - 1.2], [lng + 1.2, lat - 1.2],
                     [lng + 1.2, lat + 1.2], [lng - 1.2, lat + 1.2],
                     [lng - 1.2, lat - 1.2]])
    b.add_polygon(ring)
    chips = tessellate(b.finish(), 3, grid, keep_core_geom=True)
    from mosaic_tpu.core.index.h3.index import is_pentagon_cell
    pent_rows = np.nonzero(is_pentagon_cell(chips.cell_id) &
                           chips.is_core)[0]
    assert len(pent_rows), "box around a pentagon must core-cover it"
    for r in pent_rows:
        _, parts = chips.geoms.geom_slices(int(r))
        shell = parts[0][0]
        assert np.array_equal(shell[0], shell[-1]), "ring not closed"
        # 5 distinct vertices + closure
        assert len(np.unique(np.round(shell, 12), axis=0)) == 5


def test_clip_jit_matches_numpy_path(monkeypatch):
    """The jitted whole-bucket clip kernel must produce chips identical
    to the interpreted half-plane path (same split points, same
    emission order)."""
    import jax
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.core.tessellate import tessellate
    from mosaic_tpu.core.geometry.array import GeometryBuilder
    grid = get_index_system("H3")
    rng = np.random.default_rng(12)
    b = GeometryBuilder()
    for _ in range(30):
        cx, cy = rng.uniform(-74.2, -73.8), rng.uniform(40.6, 40.9)
        ang = 2 * np.pi * (np.arange(9) +
                           rng.uniform(-0.3, 0.3, 9)) / 9
        rad = rng.uniform(0.003, 0.02, 9)
        ring = np.stack([cx + rad * np.cos(ang),
                         cy + rad * np.sin(ang)], -1)
        b.add_polygon(np.vstack([ring, ring[:1]]))
    polys = b.finish()
    a = tessellate(polys, 8, grid, keep_core_geom=True)
    monkeypatch.setenv("MOSAIC_TPU_DISABLE_CLIP_JIT", "1")
    c = tessellate(polys, 8, grid, keep_core_geom=True)
    assert np.array_equal(a.cell_id, c.cell_id)
    assert np.array_equal(a.geom_id, c.geom_id)
    # XLA may contract a*b+c into fma, so intersection coordinates can
    # differ from numpy by ~1 ulp; chips stay self-consistent (the
    # join's recheck uses the stored coordinates)
    np.testing.assert_allclose(a.geoms.coords, c.geoms.coords,
                               rtol=0, atol=1e-9)


def test_clip_jit_concave_overflow_falls_back(monkeypatch):
    """A concave zigzag ring emits more than one vertex per clip plane
    — beyond the jit kernel's fixed width slack.  The kernel must
    detect the overflow and the chunk redo on the interpreted path,
    yielding output identical to the pure-numpy run (round-4 review:
    the convex-only width assumption silently corrupted chips)."""
    from mosaic_tpu.core.tessellate import convex_clip_tasks
    # zigzag: 24 teeth straddling y=0.5 -> ~48 crossings on one plane
    n = 24
    xs = np.linspace(0.05, 0.95, 2 * n)
    ys = np.tile([0.2, 0.8], n)
    top = np.stack([xs, ys], -1)
    ring = np.vstack([top, [[0.95, -0.5], [0.05, -0.5]]])
    # square whose BOTTOM edge is the horizontal line y=0.5 — the
    # first half-plane alone crosses all 24 teeth (~48 intersections),
    # far beyond the +1/plane width slack
    clip_verts = np.array([[[0.0, 0.5], [1.0, 0.5], [1.0, 1.0],
                            [0.0, 1.0], [0.0, 0.0], [0.0, 0.0],
                            [0.0, 0.0]]])
    clip_counts = np.array([4])
    task_ring = np.zeros(1, np.int64)
    got = convex_clip_tasks([ring], task_ring,
                            np.repeat(clip_verts, 1, axis=0),
                            clip_counts)
    monkeypatch.setenv("MOSAIC_TPU_DISABLE_CLIP_JIT", "1")
    want = convex_clip_tasks([ring], task_ring,
                             np.repeat(clip_verts, 1, axis=0),
                             clip_counts)
    assert (got[0] is None) == (want[0] is None)
    if got[0] is not None:
        np.testing.assert_array_equal(got[0], want[0])
        assert len(got[0]) > len(ring) + 7 + 1  # genuinely overflowed


def test_clip_jit_mixed_overflow_same_bucket(monkeypatch):
    """Concave (overflowing) and convex rings of the SAME size bucket
    in one jit chunk: only the overflowed ROWS redo on the interpreted
    path (bit-equal there), convex rows keep the jit result (1-ulp
    tolerance) — round-4 review: a chunk-wide redo threw away good
    work, and a grown output buffer crashed later chunks."""
    from mosaic_tpu.core.tessellate import convex_clip_tasks
    n = 24
    xs = np.linspace(0.05, 0.95, 2 * n)
    ys = np.tile([0.2, 0.8], n)
    zig = np.vstack([np.stack([xs, ys], -1),
                     [[0.95, -0.5], [0.05, -0.5]]])
    th = np.linspace(0, 2 * np.pi, 51)[:-1]
    circ = np.stack([0.5 + 0.4 * np.cos(th),
                     0.5 + 0.4 * np.sin(th)], -1)
    pool = [zig, circ]
    T = 500
    rng = np.random.default_rng(1)
    task_ring = np.where(rng.random(T) < 0.05, 0, 1).astype(np.int64)
    cv = np.tile(np.array([[[0.0, 0.5], [1.0, 0.5], [1.0, 1.0],
                            [0.0, 1.0], [0, 0], [0, 0], [0, 0]]],
                          float), (T, 1, 1))
    cc = np.full(T, 4)
    got = convex_clip_tasks(pool, task_ring, cv, cc)
    monkeypatch.setenv("MOSAIC_TPU_DISABLE_CLIP_JIT", "1")
    want = convex_clip_tasks(pool, task_ring, cv, cc)
    for i, (a, b) in enumerate(zip(got, want)):
        assert (a is None) == (b is None), i
        if a is None:
            continue
        assert a.shape == b.shape, i
        if task_ring[i] == 0:
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-9)
