"""Telemetry time-series store: rollup math, bounded memory,
snapshot/restore, the background sampler lifecycle, and the bench
watchdog's trajectory analysis.

The rollup tests compare windowed reads against brute force over the
original point stream — levels strictly partition time, so a windowed
count pins down exactly which suffix of the stream is in view and
count/sum/min/max must match that suffix exactly (quantiles are exact
only while the window sits inside the raw ring).
"""

import json
import os
import sys
import time

import pytest

import importlib

from mosaic_tpu.obs import metrics

# NB: the package re-exports the store singleton under the module's
# own name, so attribute-style module imports resolve to the store —
# go through sys.modules for the module itself.
ts_mod = importlib.import_module("mosaic_tpu.obs.timeseries")
from mosaic_tpu.obs.timeseries import (BUCKET_CAP, MAX_SERIES, RAW_CAP,
                                       Sampler, Series, TimeSeriesStore,
                                       configure_sampler, sampler,
                                       start_sampler, stop_sampler)

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import bench_watchdog  # noqa: E402


@pytest.fixture
def clean_sampler():
    """No sampler running before/after; conf latch cleared."""
    stop_sampler()
    prev_conf = ts_mod._conf_ms
    ts_mod._conf_ms = None
    yield
    stop_sampler()
    ts_mod._conf_ms = prev_conf


def make_series(values, t0=1000.0, dt=1.0):
    s = Series("t")
    for i, v in enumerate(values):
        s.append(t0 + i * dt, float(v))
    return s


# --------------------------------------------------- rollup vs brute

def test_rollups_match_bruteforce_suffix():
    n = 12_345
    vals = [((i * 37) % 1001) / 7.0 for i in range(n)]
    s = make_series(vals)
    now = 1000.0 + n            # just past the newest point
    # a spread of windows: raw-only, straddling mid, straddling
    # coarse, and all-history
    for seconds in (10, RAW_CAP // 2, RAW_CAP + 500, 4000, n + 10):
        st = s.window_stats(seconds, now=now)
        k = int(st["count"])
        assert 0 < k <= n
        suffix = vals[-k:]      # partitioned levels => a pure suffix
        assert st["sum"] == pytest.approx(sum(suffix))
        assert st["min"] == min(suffix)
        assert st["max"] == max(suffix)
        assert st["mean"] == pytest.approx(sum(suffix) / k)
    # the full-history window sees every point ever appended
    st = s.window_stats(n + 10, now=now)
    assert st["count"] == n == len(s)
    assert st["sum"] == pytest.approx(sum(vals))


def test_window_count_covers_at_least_the_cutoff():
    # a bucket straddling the cutoff is included whole: the window
    # never under-reports, and over-reports by less than one coarse
    # bucket (FOLD*FOLD points)
    n = 9_000
    s = make_series(range(n))
    now = 1000.0 + n
    for seconds in (700, 2500, 6000):
        exact = sum(1 for i in range(n)
                    if 1000.0 + i >= now - seconds)
        k = s.window_stats(seconds, now=now)["count"]
        assert exact <= k <= exact + ts_mod.FOLD * ts_mod.FOLD


def test_quantiles_exact_inside_raw_ring():
    s = make_series(range(1, 101))          # 1..100, all raw
    now = 1000.0 + 100
    assert s.quantile_over_window(50, 1000, now=now) == 50
    assert s.quantile_over_window(99, 1000, now=now) == 99
    assert s.quantile_over_window(100, 1000, now=now) == 100


def test_rate_is_exact_across_rollups():
    # counter series value = 3*i at 1 Hz => rate 3/s over any window,
    # including windows reaching into folded history
    n = 5_000
    s = make_series([3 * i for i in range(n)])
    now = 1000.0 + n
    for seconds in (50, 1000, n + 10):
        assert s.rate(seconds, now=now) == pytest.approx(3.0)
    assert Series("empty").rate(60) == 0.0


def test_fraction_over_exact_on_raw():
    s = make_series([1, 5, 9, 2, 8])
    bad, total = s.fraction_over(4.0, 1000, now=1000.0 + 5)
    assert (bad, total) == (3, 5)


# ------------------------------------------------------------ bounds

def test_series_memory_is_bounded():
    n = 200_000
    s = make_series([0.0] * 0)
    for i in range(n):
        s.append(1000.0 + i, float(i % 17))
    assert len(s.raw) <= RAW_CAP
    assert len(s.mid) <= BUCKET_CAP
    assert len(s.coarse) <= BUCKET_CAP
    assert s.dropped > 0                     # far tail really dropped
    # everything retained + everything dropped == everything appended
    assert len(s) + s.dropped * ts_mod.FOLD * ts_mod.FOLD == n


def test_store_caps_series_names():
    store = TimeSeriesStore()
    for i in range(MAX_SERIES + 10):
        store.record(f"s/{i}", 1.0, ts=1000.0)
    assert len(store) == MAX_SERIES
    assert store.names_dropped == 10
    # existing series still record fine
    store.record("s/0", 2.0, ts=1001.0)
    assert store.window_stats("s/0", 10, now=1001.0)["count"] == 2


def test_store_reads_absent_series_degrade():
    store = TimeSeriesStore()
    assert store.window_stats("nope", 60)["count"] == 0
    assert store.rate("nope", 60) == 0.0
    assert store.quantile_over_window("nope", 99, 60) == 0.0
    assert store.fraction_over("nope", 1.0, 60) == (0, 0)


# ------------------------------------------------- snapshot / restore

def test_snapshot_restore_roundtrip_through_json():
    store = TimeSeriesStore()
    for i in range(7_000):                   # deep enough to fold
        store.record("a", float(i % 13), ts=1000.0 + i)
    store.record("b", 42.0, ts=1000.0)
    snap = json.loads(json.dumps(store.snapshot()))   # wire round-trip
    other = TimeSeriesStore()
    assert other.restore(snap) == 2
    now = 1000.0 + 7_000
    for seconds in (100, 3000, 8000):
        assert other.window_stats("a", seconds, now=now) == \
            store.window_stats("a", seconds, now=now)
    assert other.rate("a", 8000, now=now) == \
        store.rate("a", 8000, now=now)
    assert other.window_stats("b", 10_000, now=now)["max"] == 42.0


def test_restore_rejects_unknown_version():
    store = TimeSeriesStore()
    assert store.restore({"version": 99, "series": {"x": {}}}) == 0
    assert store.restore("garbage") == 0
    assert len(store) == 0


# ----------------------------------------------------------- sampler

def test_sampler_tick_snapshots_registry():
    store = TimeSeriesStore()
    metrics.enable()
    try:
        metrics.count("tick/c", 5)
        metrics.gauge("tick/g", 2.5)
        metrics.observe("tick/h", 10.0)
        s = Sampler(50.0, store)
        s.tick(now=1000.0)
        s.tick(now=1001.0)
        assert s.ticks == 2
        assert store.window_stats("tick/c", 60, now=1001.0)["max"] == 5
        assert store.window_stats("tick/g", 60, now=1001.0)["max"] == 2.5
        assert store.window_stats("tick/h:count", 60,
                                  now=1001.0)["max"] == 1
        assert store.window_stats("tick/h:sum", 60,
                                  now=1001.0)["max"] == 10.0
    finally:
        metrics.disable()
        metrics.reset()


def test_sampler_start_stop_lifecycle(clean_sampler):
    store = TimeSeriesStore()
    metrics.enable()
    try:
        metrics.count("life/c")
        h = start_sampler(20.0, store)
        assert sampler() is h and h.alive
        deadline = time.time() + 5.0
        while h.ticks == 0 and time.time() < deadline:
            time.sleep(0.01)
        assert h.ticks > 0, "sampler thread never ticked"
        stop_sampler()
        assert sampler() is None and not h.alive
        assert store.series("life/c") is not None
    finally:
        metrics.disable()
        metrics.reset()


def test_configure_sampler_conf_lifecycle(clean_sampler):
    configure_sampler(30.0)
    assert sampler() is not None
    assert sampler().interval_ms == 30.0
    configure_sampler(30.0)                  # same value: no restart
    first = sampler()
    configure_sampler(30.0)
    assert sampler() is first
    configure_sampler(0.0)                   # conf stops what conf started
    assert sampler() is None


def test_configure_sampler_keeps_programmatic_sampler(clean_sampler):
    h = start_sampler(25.0)
    configure_sampler(0.0)   # a SET with cadence 0 while conf never
    assert sampler() is h    # started one must not kill this sampler
    stop_sampler()


def test_env_var_pins_cadence(clean_sampler, monkeypatch):
    monkeypatch.setenv("MOSAIC_TPU_OBS_SAMPLE_MS", "250")
    configure_sampler(30.0)                  # ignored while pinned
    assert sampler() is None


# ----------------------------------------------------- bench watchdog

def test_watchdog_tolerates_thin_history():
    r = bench_watchdog.analyze([], {"device_ms": 100.0})
    assert r["status"] == "no-history" and r["flags"] == []
    r = bench_watchdog.analyze([("1", {"device_ms": 100.0})],
                               {"device_ms": 101.0})
    assert r["status"] == "short-history" and r["flags"] == []


def test_watchdog_flags_regressions_both_directions():
    hist = [(str(i), {"device_ms": 100.0 + i, "value": 1000.0})
            for i in range(5)]
    r = bench_watchdog.analyze(hist, {"device_ms": 160.0,
                                      "value": 700.0})
    assert any(m.startswith("device_ms") for m in r["regressions"])
    assert any(m.startswith("value") for m in r["regressions"])
    assert any("device_ms" in m for m in r["variance_spikes"])
    clean = bench_watchdog.analyze(hist, {"device_ms": 103.0,
                                          "value": 1010.0})
    assert clean["flags"] == []


def test_watchdog_markdown_report():
    hist = [(str(i), {"end_to_end_ms": 50.0}) for i in range(3)]
    r = bench_watchdog.analyze(hist, {"end_to_end_ms": 49.0})
    md = bench_watchdog.to_markdown(r, platform="cpu")
    assert "# Bench watchdog (cpu)" in md
    assert "| end_to_end_ms |" in md and "- none" in md


def test_watchdog_unwraps_runner_records(tmp_path):
    inner = {"metric": "pip_join_points_per_sec", "platform": "cpu",
             "device_ms": 123.0}
    wrapper = {"n": 1, "cmd": "python bench.py", "rc": 0,
               "tail": "noise line\n" + json.dumps(inner) + "\n"}
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps(wrapper, indent=2))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(inner))
    hist = bench_watchdog.load_history(str(tmp_path), "cpu")
    assert [t for t, _ in hist] == ["01", "02"]
    assert all(r["device_ms"] == 123.0 for _, r in hist)


def test_watchdog_metric_lists_match_bench_guard():
    """The watchdog keeps local copies of the perf-guard direction
    lists; this pins them to the literals in bench.py."""
    import ast
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tree = ast.parse(open(os.path.join(root, "bench.py")).read())
    found = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id in ("lower_better", "higher_better"):
            found[node.targets[0].id] = ast.literal_eval(node.value)
    assert found["lower_better"] == bench_watchdog.LOWER_BETTER
    assert found["higher_better"] == bench_watchdog.HIGHER_BETTER


def test_watchdog_store_metrics_guard_after_two_rounds():
    """``store.*`` metrics trend from their first record but only
    join the 20% guard once TWO history rounds carry the key — the
    first round of a new bench stage must not hard-fail the guard,
    and the gated keys stay out of the pinned bench lists."""
    assert not (set(bench_watchdog.GUARD_AFTER_HISTORY)
                & set(bench_watchdog.LOWER_BETTER
                      + bench_watchdog.HIGHER_BETTER
                      + bench_watchdog.TREND_ONLY))
    bad = {"store": {"ingest_s": 20.0, "query_pts_per_s": 100.0}}
    ok = {"store": {"ingest_s": 10.0, "query_pts_per_s": 1000.0}}
    one = [("1", ok)]
    r = bench_watchdog.analyze(one, bad)
    assert r["regressions"] == []            # 1 round: trend only
    assert r["trends"]["store.ingest_s"]["direction"] == "trend"
    assert r["trends"]["store.ingest_s"]["history"] == [10.0]
    two = [("1", ok), ("2", ok)]
    r = bench_watchdog.analyze(two, bad)     # 2 rounds: guard armed
    assert any(m.startswith("store.ingest_s")
               for m in r["regressions"])
    assert any(m.startswith("store.query_pts_per_s")
               for m in r["regressions"])
    assert r["trends"]["store.ingest_s"]["direction"] == \
        "lower_better"
    clean = bench_watchdog.analyze(two, ok)
    assert clean["regressions"] == []
