"""Tracing/observability tests.

Reference counterparts: GDALCalc.scala:39-55 (last_command/last_error
tile metadata), test/SparkSuite.scala:30-36 (benchmark helper), Spark UI
timing (here: the span tracer wired into MosaicContext.call).
"""

import numpy as np
import pytest

from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.utils.trace import record_command, record_error, tracer


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture
def clean_tracer():
    tracer.reset()
    tracer.enable()
    yield tracer
    tracer.disable()
    tracer.reset()


def _tile():
    gt = GeoTransform(0.0, 0.1, 0.0, 10.0, 0.0, -0.1)
    return RasterTile(np.arange(100.0).reshape(10, 10)[None], gt)


def test_span_timing_via_call(mc, clean_tracer):
    from mosaic_tpu.core.geometry.wkt import read_wkt
    g = read_wkt(["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"])
    mc.call("st_area", g)
    mc.call("st_area", g)
    rep = clean_tracer.report()
    s = rep["spans"]["call/st_area"]
    assert s["calls"] == 2 and s["total_s"] >= 0.0
    assert "call/st_area" in clean_tracer.format_report()


def test_disabled_tracer_records_nothing(mc):
    tracer.reset()
    tracer.disable()
    from mosaic_tpu.core.geometry.wkt import read_wkt
    mc.call("st_area", read_wkt(["POINT (0 0)"]))
    assert tracer.report()["spans"] == {}


def test_nested_spans_qualify(clean_tracer):
    with clean_tracer.span("outer"):
        with clean_tracer.span("inner"):
            pass
    spans = clean_tracer.report()["spans"]
    assert "outer" in spans and "outer/inner" in spans


def test_counters(clean_tracer):
    clean_tracer.count("chips", 5)
    clean_tracer.count("chips", 2)
    assert clean_tracer.report()["counters"]["chips"] == 7


def test_map_algebra_records_last_command(mc):
    t = _tile()
    out = mc.rst_mapalgebra([t, t], lambda a, b: a + b)
    assert "map_algebra" in out.meta["last_command"]


def test_warp_records_last_command():
    from mosaic_tpu.core.raster.rops import warp
    gt = GeoTransform(-74.0, 0.01, 0.0, 41.0, 0.0, -0.01)
    t = RasterTile(np.ones((1, 20, 20)), gt, srid=4326)
    w = warp(t, 3857)
    assert w.meta["last_command"].startswith("warp(")
    assert w.meta["warped_from"] == "4326"


def test_record_error_metadata():
    t = _tile()
    record_command(t, "rst_custom(x)")
    try:
        raise RuntimeError("boom with a very long message " + "x" * 400)
    except RuntimeError as e:
        record_error(t, e)
    assert t.meta["last_command"] == "rst_custom(x)"
    assert t.meta["last_error"].startswith("RuntimeError")
    assert len(t.meta["last_error"]) <= 200
    assert "full_error" in t.meta


# ------------------------------------------------- obs layer (PR: obs)


def test_histogram_percentiles():
    from mosaic_tpu.obs import Histogram
    h = Histogram("t")
    for v in [0.001] * 95 + [1.0] * 5:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert abs(snap["sum"] - (0.095 + 5.0)) < 1e-9
    assert snap["min"] == 0.001 and snap["max"] == 1.0
    # exponential buckets are ~19% wide: p50 lands in 0.001's bucket,
    # p99 in the 1.0 tail (upper edge clipped to the observed max)
    assert 0.001 <= snap["p50"] < 0.0013
    assert 0.5 <= snap["p99"] <= 1.0
    assert snap["p50"] <= snap["p95"] <= snap["p99"]


def test_span_report_percentiles(clean_tracer):
    for _ in range(20):
        with clean_tracer.span("stage"):
            pass
    s = clean_tracer.report()["spans"]["stage"]
    assert s["calls"] == 20
    assert 0.0 <= s["p50_s"] <= s["p95_s"] <= s["p99_s"] <= s["max_s"]
    assert "p95" in clean_tracer.format_report()


def test_metrics_registry(clean_tracer):
    from mosaic_tpu.obs import metrics
    assert metrics.enabled        # tracer.enable() turns metrics on
    metrics.count("x", 2)
    metrics.count("x", 3)
    metrics.gauge("g", 1.5)
    metrics.gauge_max("gm", 1.0)
    metrics.gauge_max("gm", 3.0)
    metrics.gauge_max("gm", 2.0)
    metrics.observe("lat_s", 0.01)
    rep = metrics.report()
    assert rep["counters"]["x"] == 5
    assert rep["gauges"]["g"] == 1.5 and rep["gauges"]["gm"] == 3.0
    assert rep["histograms"]["lat_s"]["count"] == 1
    # registry values merge into the tracer's one-stop report
    trep = clean_tracer.report()
    assert trep["counters"]["x"] == 5
    assert trep["gauges"]["gm"] == 3.0


def test_disabled_metrics_record_nothing():
    from mosaic_tpu.obs import metrics
    tracer.reset()
    tracer.disable()
    assert not metrics.enabled
    metrics.count("nope", 1)
    metrics.gauge("nope_g", 1.0)
    metrics.observe("nope_h", 1.0)
    rep = metrics.report()
    assert rep["counters"] == {} and rep["gauges"] == {}
    assert rep["histograms"] == {}


def test_recompile_counter_attribution(clean_tracer):
    import jax
    import jax.numpy as jnp
    from mosaic_tpu.obs import install_jax_listeners
    install_jax_listeners()
    # a fresh lambda is a fresh jit cache entry -> guaranteed compile
    with clean_tracer.span("obs_test_compile"):
        jax.block_until_ready(
            jax.jit(lambda x: x * 1.234567 + 0.89)(jnp.arange(8.0)))
    rep = clean_tracer.report()
    assert rep["counters"].get("jax/recompiles", 0) >= 1
    assert rep["counters"].get("jax/recompiles/obs_test_compile", 0) >= 1
    assert rep["histograms"]["jax/compile_s"]["count"] >= 1


def test_chrome_trace_export(tmp_path, clean_tracer):
    import json
    with clean_tracer.span("outer"):
        with clean_tracer.span("inner"):
            pass
    from mosaic_tpu.obs import chrome_trace_events, export_chrome_trace
    doc = chrome_trace_events()
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in evs}
    assert {"outer", "outer/inner"} <= names
    for e in evs:
        assert {"name", "ph", "ts", "dur", "pid", "tid", "cat"} \
            <= set(e)
        assert e["ts"] > 0 and e["dur"] >= 0
    path = tmp_path / "trace.json"
    export_chrome_trace(str(path))
    ondisk = json.loads(path.read_text())
    assert ondisk["displayTimeUnit"] == "ms"
    assert any(e.get("ph") == "X" for e in ondisk["traceEvents"])


def test_collective_accounting_exchange(clean_tracer):
    from mosaic_tpu.parallel.overlay import _account_exchange
    cells = np.arange(32, dtype=np.int64)
    valid = np.ones(32, bool)
    _account_exchange("unit", 4, 64, 8, 4, cells, valid)
    rep = clean_tracer.report()
    # per row: cell i64 + id i32 + [8,4] f32 edges + valid bool
    row_bytes = 8 + 4 + 8 * 16 + 1
    assert rep["counters"]["collective/all_to_all_bytes"] == \
        4 * 4 * 64 * row_bytes
    assert rep["counters"]["collective/all_to_all_calls"] == 4
    assert rep["gauges"]["shard/skew/unit"] >= 1.0
    assert rep["gauges"]["shard/rows_max/unit"] >= 1.0


def test_ppermute_bytes_sharded_convolve(clean_tracer):
    import jax
    from jax.sharding import Mesh
    from mosaic_tpu.parallel.raster_halo import sharded_convolve
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    D = len(devs)
    gt = GeoTransform(0.0, 0.1, 0.0, 10.0, 0.0, -0.1)
    tile = RasterTile(
        np.arange(D * 4 * 16, dtype=np.float64).reshape(1, D * 4, 16),
        gt)
    mesh = Mesh(np.array(devs), ("data",))
    sharded_convolve(tile, np.ones((3, 3)) / 9.0, mesh)
    rep = clean_tracer.report()
    # 2 ppermute shifts x D devices x bands*halo*W f32 rows
    assert rep["counters"]["collective/ppermute_bytes"] == \
        2.0 * D * 1 * 1 * 16 * 4
    assert rep["counters"]["collective/ppermute_calls"] == 2
    assert "halo/convolve" in rep["spans"]


def test_utils_trace_shim_is_obs():
    # back-compat: utils.trace re-exports the obs singletons
    from mosaic_tpu import obs
    from mosaic_tpu.utils import trace as shim
    assert shim.tracer is obs.tracer
    assert shim.metrics is obs.metrics
