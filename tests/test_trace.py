"""Tracing/observability tests.

Reference counterparts: GDALCalc.scala:39-55 (last_command/last_error
tile metadata), test/SparkSuite.scala:30-36 (benchmark helper), Spark UI
timing (here: the span tracer wired into MosaicContext.call).
"""

import numpy as np
import pytest

from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.utils.trace import record_command, record_error, tracer


@pytest.fixture(scope="module")
def mc():
    return MosaicContext.build("CUSTOM(-180,180,-90,90,2,360,180)")


@pytest.fixture
def clean_tracer():
    tracer.reset()
    tracer.enable()
    yield tracer
    tracer.disable()
    tracer.reset()


def _tile():
    gt = GeoTransform(0.0, 0.1, 0.0, 10.0, 0.0, -0.1)
    return RasterTile(np.arange(100.0).reshape(10, 10)[None], gt)


def test_span_timing_via_call(mc, clean_tracer):
    from mosaic_tpu.core.geometry.wkt import read_wkt
    g = read_wkt(["POLYGON ((0 0, 1 0, 1 1, 0 1, 0 0))"])
    mc.call("st_area", g)
    mc.call("st_area", g)
    rep = clean_tracer.report()
    s = rep["spans"]["call/st_area"]
    assert s["calls"] == 2 and s["total_s"] >= 0.0
    assert "call/st_area" in clean_tracer.format_report()


def test_disabled_tracer_records_nothing(mc):
    tracer.reset()
    tracer.disable()
    from mosaic_tpu.core.geometry.wkt import read_wkt
    mc.call("st_area", read_wkt(["POINT (0 0)"]))
    assert tracer.report()["spans"] == {}


def test_nested_spans_qualify(clean_tracer):
    with clean_tracer.span("outer"):
        with clean_tracer.span("inner"):
            pass
    spans = clean_tracer.report()["spans"]
    assert "outer" in spans and "outer/inner" in spans


def test_counters(clean_tracer):
    clean_tracer.count("chips", 5)
    clean_tracer.count("chips", 2)
    assert clean_tracer.report()["counters"]["chips"] == 7


def test_map_algebra_records_last_command(mc):
    t = _tile()
    out = mc.rst_mapalgebra([t, t], lambda a, b: a + b)
    assert "map_algebra" in out.meta["last_command"]


def test_warp_records_last_command():
    from mosaic_tpu.core.raster.rops import warp
    gt = GeoTransform(-74.0, 0.01, 0.0, 41.0, 0.0, -0.01)
    t = RasterTile(np.ones((1, 20, 20)), gt, srid=4326)
    w = warp(t, 3857)
    assert w.meta["last_command"].startswith("warp(")
    assert w.meta["warped_from"] == "4326"


def test_record_error_metadata():
    t = _tile()
    record_command(t, "rst_custom(x)")
    try:
        raise RuntimeError("boom with a very long message " + "x" * 400)
    except RuntimeError as e:
        record_error(t, e)
    assert t.meta["last_command"] == "rst_custom(x)"
    assert t.meta["last_error"].startswith("RuntimeError")
    assert len(t.meta["last_error"]) <= 200
    assert "full_error" in t.meta
