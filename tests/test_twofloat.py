"""Double-single f32 arithmetic (ops/twofloat.py).

Exactness is asserted in eager mode: each op is its own compiled module
there, so XLA cannot contract/reassociate across the Dekker sequences.
Under a fused jit, XLA:CPU compiles `t1 - p` into fma(ahi, bhi, -p) and
similar, collapsing df to ~f32 — that platform caveat is exactly why
jaxkernel.pick_precision routes CPU to the native-f64 path; the jit-mode
assertions here only require the f32-level floor that even the collapsed
form guarantees.  The TPU lane (test_tpu.py) asserts full df precision
under jit on hardware where the transforms survive.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from mosaic_tpu.ops import twofloat as tf


def total(df):
    return np.asarray(df.hi, np.float64) + np.asarray(df.lo, np.float64)


@pytest.fixture
def vals():
    rng = np.random.default_rng(1)
    return rng.uniform(-2.0, 2.0, 64).astype(np.float32)


def test_two_sum_exact(vals):
    a = jnp.asarray(vals)
    b = jnp.asarray(vals[::-1].copy() * np.float32(1e-4))
    s, e = tf.two_sum(a, b)
    got = np.asarray(s, np.float64) + np.asarray(e, np.float64)
    want = vals.astype(np.float64) + (vals[::-1] * np.float32(1e-4)
                                      ).astype(np.float64)
    assert np.array_equal(got, want)


def test_two_prod_exact(vals):
    a = jnp.asarray(vals)
    b = jnp.asarray(vals[::-1].copy())
    p, e = tf.two_prod(a, b)
    got = np.asarray(p, np.float64) + np.asarray(e, np.float64)
    want = vals.astype(np.float64) * vals[::-1].astype(np.float64)
    assert np.array_equal(got, want)


def test_df_mul_precision(vals):
    x = tf.df_const(np.pi / 180.0)
    r = tf.df_mul(tf.df_from_f32(jnp.asarray(vals)), x)
    want = vals.astype(np.float64) * np.pi / 180.0
    assert np.max(np.abs(total(r) - want)) < 1e-10


def test_df_div_precision(vals):
    num = tf.df_const(1.0)
    den_v = np.abs(vals) + np.float32(0.5)      # f32-rounded denominator
    den = tf.df_from_f32(jnp.asarray(den_v))
    r = tf.df_div(num, den)
    want = 1.0 / den_v.astype(np.float64)
    assert np.max(np.abs(total(r) - want) / np.abs(want)) < 1e-12


def test_df_trig_small_angle():
    d = np.linspace(-0.04, 0.04, 101).astype(np.float32)
    df = tf.df_mul(tf.df_from_f32(jnp.asarray(d)), tf.df_const(1.0))
    s = tf.df_poly_sin(df)
    c = tf.df_poly_cos(df)
    assert np.max(np.abs(total(s) - np.sin(d.astype(np.float64)))) < 1e-12
    assert np.max(np.abs(total(c) - np.cos(d.astype(np.float64)))) < 1e-12


def test_df_round_carries_residual():
    v = np.array([1234.4999, -77.5001, 0.49997], np.float64)
    hi = v.astype(np.float32)
    lo = (v - hi.astype(np.float64)).astype(np.float32)
    r, frac = tf.df_round(tf.DF(jnp.asarray(hi), jnp.asarray(lo)))
    want_r = np.round(v)
    got = np.asarray(r, np.float64)
    # round-half-to-even vs true value: both residual decompositions must
    # reconstruct v
    assert np.allclose(got + np.asarray(frac, np.float64), v, atol=1e-7)
    assert np.max(np.abs(got - want_r)) <= 1.0
