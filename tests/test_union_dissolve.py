"""Parity-dissolve union: correctness, self-check fallbacks, and the
round-5 scalability contract (VERDICT round-4 task 3: 10k-chip
st_union_agg < 1 s — the round-4 fold measured 13.4 s at 5.4k chips).

Reference counterpart: ST_UnionAgg.scala / ST_IntersectionAgg.scala
(JTS CascadedPolygonUnion); ours replaces the pairwise-union tree with
boundary-parity cancellation, which is exact for interior-disjoint
inputs and self-verifying via the area identity."""

import time

import numpy as np
import pytest

from mosaic_tpu.core.geometry.clip import (dissolve_disjoint_rings,
                                           geometry_rings, _pip_rings,
                                           ring_signed_area,
                                           unary_union_rings)


def sq(x0, y0, s=1.0):
    return np.array([[x0, y0], [x0 + s, y0], [x0 + s, y0 + s],
                     [x0, y0 + s]], float)


def region_area(rings):
    return sum(ring_signed_area(r) for r in rings)


class TestDissolveToys:
    def test_adjacent_squares_merge(self):
        r = dissolve_disjoint_rings([[sq(0, 0)], [sq(1, 0)]])
        assert len(r) == 1 and region_area(r) == pytest.approx(2.0)

    def test_disjoint_squares_stay_separate(self):
        r = dissolve_disjoint_rings([[sq(0, 0)], [sq(3, 0)]])
        assert len(r) == 2 and region_area(r) == pytest.approx(2.0)

    def test_grid_of_cells_dissolves_to_one_shell(self):
        parts = [[sq(i, j)] for i in range(10) for j in range(10)]
        r = dissolve_disjoint_rings(parts)
        assert len(r) == 1 and region_area(r) == pytest.approx(100.0)

    def test_hole_plug_fills(self):
        donut = [sq(0, 0, 3), sq(1, 1, 1)[::-1]]
        r = dissolve_disjoint_rings([donut, [sq(1, 1, 1)]])
        assert len(r) == 1 and region_area(r) == pytest.approx(9.0)

    def test_hole_preserved_with_orientation(self):
        donut = [sq(0, 0, 3), sq(1, 1, 1)[::-1]]
        r = dissolve_disjoint_rings([donut, [sq(5, 5)]])
        areas = sorted(ring_signed_area(x) for x in r)
        assert areas == pytest.approx([-1.0, 1.0, 9.0])
        assert region_area(r) == pytest.approx(9.0)

    def test_duplicated_part_rejected(self):
        # identical copies cancel to nothing: caught, not silently empty
        assert dissolve_disjoint_rings([[sq(0, 0)], [sq(0, 0)]]) is None

    def test_nested_overlap_rejected(self):
        # B strictly inside A: boundary survives as a hole, area
        # identity fails
        assert dissolve_disjoint_rings(
            [[sq(0, 0, 3)], [sq(1, 1, 1)]]) is None

    def test_cw_input_rings_are_reoriented(self):
        r = dissolve_disjoint_rings([[sq(0, 0)[::-1]], [sq(1, 0)]])
        assert len(r) == 1 and region_area(r) == pytest.approx(2.0)

    def test_split_mismatch_healed_or_rejected(self):
        # right square's shared wall vertices off by 3e-7: either the
        # repair pass heals it (area within tol) or it is rejected —
        # never a silently wrong answer
        b = sq(1, 0).copy()
        b[0, 0] += 3e-7
        r = dissolve_disjoint_rings([[sq(0, 0)], [b]])
        if r is not None:
            assert region_area(r) == pytest.approx(2.0, abs=1e-5)

    def test_unary_union_rings_general_path_resolves_overlap(self):
        # the general entry point must NOT take the dissolve shortcut
        # for overlapping inputs (no assume_disjoint)
        out = unary_union_rings(
            [[sq(0, 0)], [sq(0.5, 0)], [sq(5, 0)], [sq(6, 0)],
             [sq(7, 0)]])
        from mosaic_tpu.core.geometry.clip import _normalize_rings
        a = sum(ring_signed_area(r) for r in _normalize_rings(out))
        assert a == pytest.approx(1.5 + 3.0, abs=1e-6)


class TestUnionAggRealZones:
    @pytest.fixture(scope="class")
    def zones(self):
        import json
        import os
        p = os.path.join(os.path.dirname(__file__), "data",
                         "nyc_taxi_zones.geojson")
        from mosaic_tpu.core.geometry.geojson import read_geojson
        feats = [json.loads(l) for l in open(p) if l.strip()]
        return read_geojson([json.dumps(f["geometry"]) for f in feats])

    def test_union_agg_exact_and_fast(self, zones):
        from mosaic_tpu.core.index.factory import get_index_system
        from mosaic_tpu.core.tessellate import tessellate
        from mosaic_tpu.functions.context import MosaicContext
        grid = get_index_system("H3")
        ctx = MosaicContext.build(grid)
        chips = tessellate(zones, 10, grid, keep_core_geom=False)
        assert len(chips.cell_id) > 5000
        t0 = time.time()
        u = ctx.st_union_agg(chips)
        dt = time.time() - t0
        # exactness: union-of-chips membership == any-zone membership
        rng = np.random.default_rng(7)
        pts = np.stack([rng.uniform(-74.05, -73.90, 2000),
                        rng.uniform(40.68, 40.83, 2000)], -1)
        urings = [r for gi in range(len(u))
                  for r in geometry_rings(u, gi)]
        in_u = _pip_rings(pts, urings)
        in_z = np.zeros(len(pts), bool)
        for gi in range(len(zones)):
            in_z |= _pip_rings(pts, geometry_rings(zones, gi))
        assert int(np.sum(in_u != in_z)) == 0
        # area identity against the source zones (disjoint partition)
        from mosaic_tpu.core.geometry.clip import _normalize_rings
        ua = sum(ring_signed_area(r) for gi in range(len(u))
                 for r in _normalize_rings(geometry_rings(u, gi)))
        za = sum(abs(sum(ring_signed_area(rr) for rr in
                         _normalize_rings(geometry_rings(zones, gi))))
                 for gi in range(len(zones)))
        # rel 1e-4: the vertex-heal pass (shared-wall vertices in real
        # data agree only to ~1e-6 deg) perturbs area by O(heal radius
        # x wall length) — measured ~2e-6 relative here, versus the
        # old fold's snap-floor losses at 1e-1 relative
        assert ua == pytest.approx(za, rel=1e-4)
        # the scalability contract (generous CI headroom over the
        # ~0.6 s measured: the round-4 fold took ~25 s at this scale)
        assert dt < 5.0
