"""MVT / GeoJSON tile aggregators + analyzer + misc round-3 surface."""

import json

import numpy as np
import pytest

from mosaic_tpu.bench.workloads import nyc_zones
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.functions.context import MosaicContext
from mosaic_tpu.io.vectortile import (decode_mvt, st_asmvttileagg,
                                      st_asgeojsontileagg,
                                      tile_envelope_4326)


@pytest.fixture(scope="module")
def zones():
    return nyc_zones(n_side=4, seed=2)


def _nyc_tile():
    # a z12 tile over lower Manhattan-ish
    import math
    z = 12
    lon, lat = -74.0, 40.72
    n = 2 ** z
    x = int((lon + 180) / 360 * n)
    y = int((1 - math.asinh(math.tan(math.radians(lat))) / math.pi)
            / 2 * n)
    return z, x, y


def test_mvt_round_trip(zones):
    z, x, y = _nyc_tile()
    attrs = {"zone": [f"z{i}" for i in range(len(zones))],
             "score": list(range(len(zones)))}
    blob = st_asmvttileagg(zones, attrs, z, x, y, layer="zones")
    assert isinstance(blob, bytes) and len(blob) > 20
    dec = decode_mvt(blob)
    lay = dec["zones"]
    assert lay["version"] == 2 and lay["extent"] == 4096
    assert len(lay["features"]) > 0
    assert lay["keys"] == ["zone", "score"]
    for f in lay["features"]:
        assert f["type"] == 3                      # polygons
        for ring in f["rings"]:
            assert len(ring) >= 3
            assert (ring >= -2).all() and (ring <= 4098).all()
        # tags reference valid key/value slots
        tags = f["tags"]
        for ki, vi in zip(tags[0::2], tags[1::2]):
            assert ki < len(lay["keys"]) and vi < len(lay["values"])
    # the source attribute values survive
    assert any(v == "z0" or str(v).startswith("z")
               for v in lay["values"])


def test_mvt_empty_tile(zones):
    blob = st_asmvttileagg(zones, None, 12, 0, 0)     # far away tile
    dec = decode_mvt(blob)
    assert len(dec["layer"]["features"]) == 0


def test_geojson_tile_agg(zones):
    z, x, y = _nyc_tile()
    out = st_asgeojsontileagg(zones, {"i": list(range(len(zones)))},
                              z, x, y)
    fc = json.loads(out)
    assert fc["type"] == "FeatureCollection"
    assert len(fc["features"]) > 0
    box = tile_envelope_4326(z, x, y)
    for f in fc["features"]:
        assert f["geometry"]["type"] in ("MultiPolygon", "Polygon")
        coords = np.array(f["geometry"]["coordinates"][0][0])
        assert (coords[:, 0] >= box[0] - 1e-9).all()
        assert (coords[:, 0] <= box[2] + 1e-9).all()


def test_analyzer_optimal_resolution(zones):
    mc = MosaicContext.build("H3")
    res = mc.get_optimal_resolution(zones)
    assert res in mc.index_system.resolutions()
    # zones ~2km wide: plausible band
    assert 6 <= res <= 10


def test_try_sql(zones):
    mc = MosaicContext.build("H3")
    assert mc.try_sql(mc.st_geomfromwkt, ["POINT(1 2)"]) is not None
    assert mc.try_sql(mc.st_geomfromwkt, ["POINT(1"]) is None


def test_read_strategies(tmp_path, zones):
    from mosaic_tpu.core.raster.checkpoint import deserialize_tile
    from mosaic_tpu.core.raster.gtiff import write_gtiff
    from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile
    from mosaic_tpu.io.raster_grid import read_gtiff_files
    gt = GeoTransform(-74.1, 0.01, 0.0, 40.9, 0.0, -0.01)
    t = RasterTile(np.arange(600.0).reshape(1, 20, 30), gt)
    p = str(tmp_path / "t.tif")
    open(p, "wb").write(write_gtiff(t))
    mem = read_gtiff_files([p])
    assert len(mem) == 1 and mem[0].width == 30
    recs = read_gtiff_files([p], strategy="as_path")
    assert recs[0]["raster"] == p
    back = deserialize_tile(recs[0])
    np.testing.assert_allclose(np.asarray(back.data),
                               np.asarray(t.data))
    with pytest.raises(ValueError):
        read_gtiff_files([p], strategy="bogus")


def test_call_by_name(zones):
    mc = MosaicContext.build("H3")
    area = mc.call("st_area", zones)
    assert len(area) == len(zones)
    with pytest.raises(ValueError):
        mc.call("st_nonexistent", zones)
