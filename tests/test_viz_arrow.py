"""Viz export + Arrow interchange (utils/viz.py, io/arrow.py)."""

import json

import numpy as np
import pytest

from mosaic_tpu.bench.workloads import nyc_zones
from mosaic_tpu.core.index.factory import get_index_system
from mosaic_tpu.core.tessellate import tessellate
from mosaic_tpu.io.arrow import (chips_from_arrow, chips_to_arrow,
                                 table_from_ipc, table_to_ipc)
from mosaic_tpu.utils.viz import (cells_to_geojson, chips_to_geojson,
                                  render_svg)


@pytest.fixture(scope="module")
def chips():
    zones = nyc_zones(3, seed=4)
    return tessellate(zones, 8, get_index_system("H3")), zones


def test_chips_geojson(chips):
    cs, zones = chips
    fc = json.loads(chips_to_geojson(cs))
    assert len(fc["features"]) == len(cs)
    f0 = fc["features"][0]
    assert set(f0["properties"]) == {"cell_id", "geom_id", "is_core"}


def test_cells_geojson(chips):
    cs, _ = chips
    grid = get_index_system("H3")
    cells = np.unique(cs.cell_id)[:20]
    vals = {int(c): float(i) for i, c in enumerate(cells)}
    fc = json.loads(cells_to_geojson(cells, grid, vals))
    assert len(fc["features"]) == 20
    assert fc["features"][3]["properties"]["value"] == 3.0
    ring = fc["features"][0]["geometry"]["coordinates"][0]
    assert ring[0] == ring[-1]


def test_render_svg(chips):
    _, zones = chips
    svg = render_svg(zones, values=list(range(len(zones))))
    assert svg.startswith("<svg") and svg.endswith("</svg>")
    assert svg.count("<path") == len(zones)


def test_arrow_round_trip(chips):
    cs, _ = chips
    table = chips_to_arrow(cs)
    blob = table_to_ipc(table)
    back = chips_from_arrow(table_from_ipc(blob))
    assert np.array_equal(back.cell_id, cs.cell_id)
    assert np.array_equal(back.geom_id, cs.geom_id)
    assert np.array_equal(back.is_core, cs.is_core)
    # chip geometry round-trips through WKB exactly
    assert np.allclose(back.geoms.coords[:, :2], cs.geoms.coords[:, :2])
