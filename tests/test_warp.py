"""Raster reprojection, rasterize and DTM (core/raster/rops.py round 3).

Reference behaviors: RasterProject.scala:45 (warp), GDALRasterize.scala
:155 (burn), RST_DTMFromGeoms (TIN -> raster).
"""

import numpy as np
import pytest

from mosaic_tpu.core.geometry.array import GeometryBuilder
from mosaic_tpu.core.geometry.crs import transform_xy
from mosaic_tpu.core.raster import rops
from mosaic_tpu.core.raster.tile import GeoTransform, RasterTile


def _gradient_tile(w=64, h=48, srid=4326):
    gt = GeoTransform(-74.1, 0.002, 0.0, 40.9, 0.0, -0.002)
    yy, xx = np.mgrid[0:h, 0:w]
    data = (xx * 2.0 + yy * 3.0)[None].astype(np.float64)
    return RasterTile(data, gt, nodata=None, srid=srid)


def test_warp_preserves_world_values():
    """Warp to 3857: sampling the warped raster at a world point must
    approximate the source value at the same world point."""
    t = _gradient_tile()
    w = rops.warp(t, 3857)
    assert w.srid == 3857
    rng = np.random.default_rng(3)
    lon = rng.uniform(-74.08, -74.0, 50)
    lat = rng.uniform(40.82, 40.88, 50)
    m = transform_xy(np.stack([lon, lat], -1), 4326, 3857)
    cw, rw = w.gt.to_raster(m[:, 0], m[:, 1])
    cs, rs = t.gt.to_raster(lon, lat)
    vw = np.asarray(w.data[0])[rw.astype(int), cw.astype(int)]
    vs = np.asarray(t.data[0])[rs.astype(int), cs.astype(int)]
    # bilinear interpolation of a linear gradient is exact up to pixel
    # quantization of the lookup
    assert np.max(np.abs(vw - vs)) < 6.0


def test_warp_round_trip_identityish():
    t = _gradient_tile()
    back = rops.warp(rops.warp(t, 3857), 4326)
    # compare on the interior (edges lose a pixel to the bbox pad)
    a = np.asarray(t.data[0])[8:-8, 8:-8]
    b = np.asarray(back.data[0])
    # align: sample back at source pixel centers
    cols = np.arange(t.width) + 0.5
    rows = np.arange(t.height) + 0.5
    gx, gy = np.meshgrid(cols, rows)
    wx, wy = t.gt.to_world(gx, gy)
    cc, rr = back.gt.to_raster(wx.ravel(), wy.ravel())
    vv = b[np.clip(rr.astype(int), 0, back.height - 1),
           np.clip(cc.astype(int), 0, back.width - 1)]
    vv = vv.reshape(t.height, t.width)[8:-8, 8:-8]
    finite = np.isfinite(vv)
    assert finite.mean() > 0.99
    assert np.nanmax(np.abs(vv - a)) < 8.0


def test_warp_rejects_unknown_epsg():
    t = _gradient_tile()
    with pytest.raises(ValueError):
        rops.warp(t, 9999)


def test_rasterize_burn_order_and_values():
    b = GeometryBuilder()
    b.add_polygon(np.array([[1.0, 1.0], [9.0, 1.0], [9.0, 9.0],
                            [1.0, 9.0], [1.0, 1.0]]))
    b.add_polygon(np.array([[4.0, 4.0], [8.0, 4.0], [8.0, 8.0],
                            [4.0, 8.0], [4.0, 4.0]]))
    geoms = b.finish()
    gt = GeoTransform(0.0, 0.5, 0.0, 10.0, 0.0, -0.5)
    tile = rops.rasterize(geoms, [1.0, 2.0], gt, 20, 20, fill=0.0)
    d = np.asarray(tile.data[0])
    # center of the inner square -> second geometry wins (burn order)
    c, r = gt.to_raster(6.0, 6.0)
    assert d[int(r), int(c)] == 2.0
    c, r = gt.to_raster(2.0, 2.0)
    assert d[int(r), int(c)] == 1.0
    assert (d == 0.0).sum() > 0


def test_dtm_from_geoms_linear_surface():
    """A TIN over samples of a plane must reproduce the plane."""
    rng = np.random.default_rng(5)
    xy = rng.uniform(0, 10, (60, 2))
    corners = np.array([[0, 0], [10, 0], [0, 10], [10, 10.0]])
    xy = np.vstack([xy, corners])
    z = 2.0 * xy[:, 0] - 0.5 * xy[:, 1] + 3.0
    pts = np.column_stack([xy, z])
    gt = GeoTransform(0.0, 0.25, 0.0, 10.0, 0.0, -0.25)
    tile = rops.dtm_from_geoms(pts, gt, 40, 40)
    d = np.asarray(tile.data[0])
    cols = np.arange(40) + 0.5
    rows = np.arange(40) + 0.5
    gx, gy = np.meshgrid(cols, rows)
    wx, wy = gt.to_world(gx, gy)
    want = 2.0 * wx - 0.5 * wy + 3.0
    finite = np.isfinite(d)
    assert finite.mean() > 0.95
    assert np.nanmax(np.abs(d[finite] - want[finite])) < 1e-9


def test_raster_to_grid_warps_foreign_crs(tmp_path):
    """raster_to_grid accepts a tile in 3857 against the H3 (4326) grid
    by warping first (reference: RasterTessellate projects per tile)."""
    from mosaic_tpu.core.index.factory import get_index_system
    from mosaic_tpu.io.raster_grid import raster_to_grid
    t = _gradient_tile()
    tm = rops.warp(t, 3857)
    grid = get_index_system("H3")
    a = raster_to_grid([t], 7, grid)
    bm = raster_to_grid([tm], 7, grid)
    common = sorted(set(a) & set(bm))
    assert len(common) > 3
    va = np.array([a[c] for c in common])
    vb = np.array([bm[c] for c in common])
    assert np.max(np.abs(va - vb) / np.maximum(np.abs(va), 1)) < 0.1
