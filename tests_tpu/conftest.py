"""TPU-device test lane (VERDICT.md round-2 item 9).

The main suite (tests/) pins an 8-device virtual CPU mesh; nothing there
ever exercises real-device numerics, so a TPU-specific drift (matmul
precision defaults, transcendental lowering, compiler contraction of the
double-single transforms) would ship invisibly.  This lane runs the same
exactness contracts on the real chip:

    python -m pytest tests_tpu -q

Every test is skipped when no TPU initializes.  The axon backend HANGS
(rather than erroring) when its tunnel is down, so availability is
probed in a bounded subprocess first — same pattern as bench.py.
"""

import os
import subprocess
import sys

import pytest


def _tpu_available() -> bool:
    if os.environ.get("MOSAIC_TPU_TESTS_FORCE_SKIP"):
        return False
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=150)
        return r.returncode == 0 and "cpu" not in r.stdout
    except subprocess.TimeoutExpired:
        return False


_AVAILABLE = None


def pytest_collection_modifyitems(config, items):
    global _AVAILABLE
    if _AVAILABLE is None:
        _AVAILABLE = _tpu_available()
    if not _AVAILABLE:
        skip = pytest.mark.skip(reason="no TPU device reachable")
        for item in items:
            item.add_marker(skip)
