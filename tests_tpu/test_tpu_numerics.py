"""Real-TPU numerics: the contracts the CPU suite cannot check.

1. Double-single (df) arithmetic survives the TPU compiler under jit —
   XLA:CPU contracts `t1 - p` into fma and collapses df to f32 (see
   ops/twofloat.py); the df-on-TPU design depends on the TPU compiler
   NOT doing that.  If this test fails, the PIP join must stop using
   precision="df" and fall back to "f32" with its wider margin band.
2. The df-local projection's margin contract on device.
3. The dense PIP join end-to-end against the f64 host oracle.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jaxmod():
    import jax
    jax.config.update("jax_enable_x64", True)
    return jax


def test_df_survives_tpu_jit(jaxmod):
    jax = jaxmod
    import jax.numpy as jnp
    from mosaic_tpu.ops import twofloat as tf

    rng = np.random.default_rng(2)
    vals = rng.uniform(-2.0, 2.0, 4096).astype(np.float32)
    pi180 = tf.df_const(np.pi / 180.0)

    def f(a):
        d = tf.df_mul(tf.df_from_f32(a), pi180)
        s = tf.df_poly_sin(d)
        return s.hi, s.lo

    hi, lo = jax.jit(f)(jnp.asarray(vals))
    got = np.asarray(hi, np.float64) + np.asarray(lo, np.float64)
    want = np.sin(vals.astype(np.float64) * np.pi / 180.0)
    err = np.abs(got - want).max()
    # df-level: ~1e-12; a collapsed (f32) chain would show ~1e-8
    assert err < 1e-10, f"df collapsed under TPU jit: {err:.2e}"


def test_projection_margin_contract_df(jaxmod):
    jax = jaxmod
    import jax.numpy as jnp
    from mosaic_tpu.core.index.h3 import hexmath as hm
    from mosaic_tpu.core.index.h3.jaxkernel import (err_lattice_bound,
                                                    project_lattice_jax)

    r = np.random.default_rng(3)
    origin = np.array([-74.0, 40.7])
    res = 9
    n = 500_000
    loc = np.stack([r.uniform(-0.4, 0.4, n),
                    r.uniform(-0.3, 0.3, n)], -1)
    latlng = np.radians((loc + origin[None])[:, ::-1])
    fh, hex2d = hm.project_lattice(latlng, res)
    ijk = hm.hex2d_to_ijk(hex2d)
    ah, bh = ijk[:, 0] - ijk[:, 2], ijk[:, 1] - ijk[:, 2]
    fd, ad, bd, margin, gap = [np.asarray(v) for v in jax.jit(
        lambda p: project_lattice_jax(p, res, origin, precision="df"))(
        jnp.asarray(loc, jnp.float32))]
    dis = ~((fd == fh) & (ad == ah) & (bd == bh))
    bound = err_lattice_bound(res, "df", 0.4)
    unflagged = dis & (margin >= bound)
    assert unflagged.sum() == 0, (
        f"{unflagged.sum()} unflagged disagreements; worst margin "
        f"{margin[dis].max():.3e} vs bound {bound:.3e}")


def test_dense_join_parity_on_tpu(jaxmod):
    jax = jaxmod
    import jax.numpy as jnp
    from mosaic_tpu.bench.workloads import build_workload, nyc_points
    from mosaic_tpu.parallel.pip_join import (DensePIPIndex,
                                              build_pip_index,
                                              host_recheck_fn, localize,
                                              make_pip_join_fn,
                                              pip_host_truth)

    polys, grid, res = build_workload(n_side=5, grid_name="H3",
                                      zones="taxi")
    idx = build_pip_index(polys, res, grid)
    assert isinstance(idx, DensePIPIndex)
    fn = jax.jit(make_pip_join_fn(idx, grid))
    pts64 = nyc_points(100_000, seed=7)
    zone, unc = fn(jnp.asarray(localize(idx, pts64)))
    zone, unc = np.asarray(zone), np.asarray(unc)
    final = host_recheck_fn(idx)(pts64, zone, unc)
    truth = pip_host_truth(pts64, polys)
    assert np.array_equal(final, truth)
    assert unc.mean() < 5e-3


def test_pallas_projection_on_tpu(jaxmod):
    """The Pallas projection kernel compiles and honours the df margin
    contract on real hardware (interpret mode cannot check either)."""
    jax = jaxmod
    import jax.numpy as jnp
    from mosaic_tpu.core.index.h3 import hexmath as hm
    from mosaic_tpu.core.index.h3.jaxkernel import err_lattice_bound
    from mosaic_tpu.ops.pallas_projection import project_lattice_pallas

    r = np.random.default_rng(8)
    origin = (-74.0, 40.7)
    res = 9
    n = 200_000
    loc = np.stack([r.uniform(-0.4, 0.4, n),
                    r.uniform(-0.3, 0.3, n)], -1).astype(np.float32)
    fd, ad, bd, margin, gap = [np.asarray(v) for v in
                               project_lattice_pallas(
        jnp.asarray(loc), res, origin)]
    latlng = np.radians((loc.astype(np.float64) +
                         np.asarray(origin)[None])[:, ::-1])
    fh, hex2d = hm.project_lattice(latlng, res)
    ijk = hm.hex2d_to_ijk(hex2d)
    ah, bh = ijk[:, 0] - ijk[:, 2], ijk[:, 1] - ijk[:, 2]
    dis = ~((fd == fh) & (ad == ah) & (bd == bh))
    bound = err_lattice_bound(res, "df", 0.4)
    assert not np.any(dis & (margin >= bound)), (
        f"{np.sum(dis & (margin >= bound))} unflagged disagreements")


def test_canonical_ids_on_tpu(jaxmod):
    """The device encode must produce canonical Uber H3 ids on REAL
    TPU hardware (round-4: the host path is vector-pinned; this pins
    the df/f32 device path's id bits on the chip)."""
    import numpy as np
    jax = jaxmod
    import jax.numpy as jnp
    from mosaic_tpu.core.index.h3.jaxkernel import latlng_to_cell_jax
    lat = jnp.asarray(np.radians([37.3615593]), jnp.float32)
    lng = jnp.asarray(np.radians([-122.0553238]), jnp.float32)
    cell = np.asarray(jax.jit(
        lambda a, b: latlng_to_cell_jax(a, b, 5))(lat, lng))[0]
    assert format(int(cell), "x") == "85283473fffffff"
    # host/device agreement on a batch
    from mosaic_tpu.core.index.h3 import index as ix
    rng = np.random.default_rng(3)
    pts = np.stack([np.arcsin(rng.uniform(-1, 1, 20000)),
                    rng.uniform(-np.pi, np.pi, 20000)], -1)
    host = ix.latlng_to_cell(pts, 7)
    dev = np.asarray(jax.jit(
        lambda a, b: latlng_to_cell_jax(a, b, 7))(
            jnp.asarray(pts[:, 0], jnp.float32),
            jnp.asarray(pts[:, 1], jnp.float32)))
    agree = (host == dev).mean()
    assert agree > 0.98, agree
