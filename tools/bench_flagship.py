"""Minimal flagship-join timer for regression bisection.

Reproduces exactly the bench.py steady-state flagship measurement
(BASELINE config 1: taxi zones x 4M points, H3 res from workload) on
CPU, printing one JSON line with device_ms / e2e_ms / uncertain_frac.
Used to bisect the r3->r4 52% device-time regression (VERDICT task 2).
"""
import json
import sys
import time

import numpy as np


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from mosaic_tpu.bench.workloads import build_workload, nyc_points
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              host_recheck_fn, localize,
                                              make_pip_join_fn,
                                              zone_histogram)

    polys, grid, res = build_workload(n_side=16, grid_name="H3",
                                      zones="taxi")
    idx = build_pip_index(polys, res, grid)
    join = make_pip_join_fn(idx, grid)
    n_zones = len(polys)
    recheck = host_recheck_fn(idx, polys)

    def step(points):
        zone, uncertain = join(points)
        return zone, uncertain, zone_histogram(zone, n_zones)

    stepc = jax.jit(step)
    n = 1 << 22
    pts64 = nyc_points(n)
    pts = jnp.asarray(localize(idx, pts64))
    t0 = time.time()
    jax.block_until_ready(stepc(pts))
    compile_s = time.time() - t0

    iters = 5
    host_batches = [nyc_points(n, seed=100 + i) for i in range(iters)]
    batches = [jax.device_put(jnp.asarray(localize(idx, hb)))
               for hb in host_batches]
    jax.block_until_ready(batches)
    dev_times, e2e_times, unc_total = [], [], 0
    for i in range(iters):
        t0 = time.time()
        z, u, h = stepc(batches[i])
        jax.block_until_ready((z, u, h))
        t1 = time.time()
        zh = recheck(host_batches[i], np.asarray(z), np.asarray(u))
        t2 = time.time()
        dev_times.append(t1 - t0)
        e2e_times.append(t2 - t0)
        unc_total += int(np.asarray(u).sum())
    print(json.dumps({
        "device_ms": round(float(np.median(dev_times)) * 1e3, 1),
        "e2e_ms": round(float(np.median(e2e_times)) * 1e3, 1),
        "uncertain_frac": round(unc_total / (iters * n), 8),
        "compile_s": round(compile_s, 1),
        "index": type(idx).__name__,
        "num_chips": idx.num_chips,
    }), flush=True)


if __name__ == "__main__":
    main()
