"""Bench-trajectory watchdog: regression + anomaly analysis over the
``BENCH_r*.json`` history.

The perf guard in bench.py answers one binary question per run — "did
any tracked metric slip >20% against the median of the last 3
same-platform records?".  This watchdog reads the SAME trajectory but
reports more:

* **regressions** — the guard's median-of-last-3 comparison, repeated
  here so the markdown report is self-contained;
* **variance spikes** — a metric whose current value sits far outside
  the historical spread (``|current - median| > var_factor * stdev``)
  even when it has not crossed the 20% slip line; a noisy metric is a
  warning that the NEXT guard verdict may be a coin flip;
* **trends** — per-metric trajectory (oldest -> newest -> current) so
  a slow drift that never trips the per-round guard is visible.

History tolerance: an empty history yields status ``no-history`` and
a single record yields ``short-history`` — both report trends only
(no stdev exists to flag spikes against, no meaningful median to call
regressions against with one point) and never raise.

Standalone by design: the metric lists are local copies of the bench
perf-guard lists, so importing this module never imports bench.py
(whose import pulls the whole mosaic stack).  Run as a CLI it analyzes
the newest record against the rest::

    python tools/bench_watchdog.py [--platform cpu] [--dir PATH]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
from typing import Dict, List, Optional, Tuple

__all__ = ["LOWER_BETTER", "HIGHER_BETTER", "TREND_ONLY",
           "GUARD_AFTER_HISTORY",
           "load_history", "analyze", "to_markdown", "main"]

# Local copies of bench.perf_guard's metric direction lists (kept in
# sync by tests/test_timeseries.py::test_watchdog_metric_lists).
LOWER_BETTER = ["device_ms", "end_to_end_ms", "flagship_join_p95_ms",
                "planner_flagship_ms", "fused_flagship_ms",
                "refined_flagship_ms",
                "serving_p95_ms",
                "sharded_end_to_end_ms",
                "tessellate_zones_s",
                "tessellate_counties_s", "overlay_s",
                "overlay_area_s", "real_zones_join_s",
                "union_agg_s",
                "raster_to_grid_s"]
HIGHER_BETTER = ["value", "knn_rows_per_sec", "sharded_pts_per_sec"]

# Tracked for drift only (trends + variance spikes), never a guard
# regression: device-memory footprint has no 20%-slip contract, but a
# creeping peak is exactly the slow leak the trend table exists to
# surface.  Dotted keys reach into nested record blocks.
TREND_ONLY = ["memory.flagship_peak_bytes",
              "memory.flagship_peak_bytes_per_row",
              # workload history plane: write volume and compaction
              # yield drift, plus the partition-heat skew trajectory
              "history.records_written",
              "history.compaction_ratio",
              "history.heat.skew",
              # adaptive join refinement: what fraction of occupied
              # cells the probe sent deep, and the layout advisor's
              # chosen grid — drift in either means the workload (or
              # the learned coefficients) moved
              "refine.cells_refined_frac",
              "layout.chosen_res"]

# Out-of-core store metrics (the bench's "store" block, first recorded
# in BENCH_r07): trended from their first appearance, but they join
# the 20% regression guard only once at least TWO history rounds carry
# the key — a brand-new stage's single round is not a baseline, and
# guarding against it would turn ordinary round-to-round noise into a
# hard failure.  Values are the guard direction once armed.
GUARD_AFTER_HISTORY = {"store.ingest_s": "lower",
                       "store.query_pts_per_s": "higher"}


def _num(rec: dict, key: str) -> Optional[float]:
    v: object = rec
    for part in key.split("."):
        if not isinstance(v, dict):
            return None
        v = v.get(part)
    return float(v) if isinstance(v, (int, float)) and v else None


def _unwrap(rec: dict) -> Optional[dict]:
    """A BENCH file is either the bench record itself or a runner
    wrapper ``{"n", "cmd", "rc", "tail"}`` whose ``tail`` captures the
    bench stdout — the record is then the last JSON line inside it.
    Wrappers may also carry the record pre-parsed under ``parsed``,
    which survives even when the captured tail was truncated mid-line
    (a truncated tail used to silently drop the round from history)."""
    if not isinstance(rec, dict):
        return None
    if "metric" in rec or "platform" in rec:
        return rec
    parsed = rec.get("parsed")
    if isinstance(parsed, dict) and ("metric" in parsed
                                     or "platform" in parsed):
        return parsed
    tail = rec.get("tail")
    if not isinstance(tail, str):
        return None
    found = None
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and ("metric" in obj
                                      or "platform" in obj):
            found = obj
    return found


def load_history(directory: str,
                 platform: Optional[str] = None
                 ) -> List[Tuple[str, dict]]:
    """``(round_tag, record)`` pairs from ``BENCH_r*.json`` under
    ``directory``, oldest first, optionally filtered to one platform.
    Unreadable/empty files are skipped, mirroring the bench guard."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                raw = f.read().strip()
            try:            # whole-file JSON (pretty-printed records)
                rec = json.loads(raw)
            except ValueError:  # JSONL: newest record is the last line
                rec = json.loads(raw.splitlines()[-1])
        except (OSError, ValueError, IndexError):
            continue
        rec = _unwrap(rec)
        if rec is None:
            continue
        if platform is not None and rec.get("platform") != platform:
            continue
        m = re.search(r"BENCH_r(\d+)", path)
        out.append((m.group(1) if m else path, rec))
    return out


def analyze(history: List[Tuple[str, dict]], current: dict,
            slip: float = 0.20, window: int = 3,
            var_factor: float = 3.0) -> dict:
    """Compare ``current`` against the ``history`` trajectory.

    ``history`` is ``(tag, record)`` pairs oldest first (the shape
    :func:`load_history` returns; bare record dicts are accepted too).
    Returns ``{"status", "regressions", "variance_spikes", "trends",
    "flags"}`` where ``flags`` is the flat human-readable union the
    caller can log line by line.  Never raises on thin history."""
    hist: List[Tuple[str, dict]] = [
        h if isinstance(h, tuple) else (str(i), h)
        for i, h in enumerate(history)]
    status = ("no-history" if not hist
              else "short-history" if len(hist) < 2 else "ok")
    recent = hist[-window:]
    tags = "+".join(t for t, _ in recent)

    regressions: List[str] = []
    spikes: List[str] = []
    trends: Dict[str, dict] = {}
    for key in (LOWER_BETTER + HIGHER_BETTER + TREND_ONLY
                + sorted(GUARD_AFTER_HISTORY)):
        lower = key in LOWER_BETTER or \
            GUARD_AFTER_HISTORY.get(key) == "lower"
        cur = _num(current, key)
        traj = [v for v in (_num(r, key) for _, r in hist)
                if v is not None]
        # history-gated keys stay trend-only until the trajectory
        # itself (current excluded) holds two rounds to baseline on
        trend_only = key in TREND_ONLY or (
            key in GUARD_AFTER_HISTORY and len(traj) < 2)
        if cur is None and not traj:
            continue
        trends[key] = {
            "history": [round(v, 3) for v in traj],
            "current": round(cur, 3) if cur is not None else None,
            "direction": ("trend" if trend_only
                          else "lower_better" if lower
                          else "higher_better"),
        }
        if cur is None:
            continue
        base_vals = [v for v in (_num(r, key) for _, r in recent)
                     if v is not None]
        if base_vals:
            base = statistics.median(base_vals)
            trends[key]["baseline"] = round(base, 3)
            ratio = cur / base if base else None
            if ratio is not None and not trend_only and (
                    ratio > 1.0 + slip if lower else ratio < 1.0 - slip):
                regressions.append(
                    f"{key}: median {base:g} -> {cur:g} "
                    f"({(ratio - 1) * 100:+.0f}% vs r{tags})")
        # variance spike: needs a real spread to measure against
        if len(traj) >= 3:
            med = statistics.median(traj)
            sd = statistics.stdev(traj)
            if sd > 0 and abs(cur - med) > var_factor * sd:
                spikes.append(
                    f"{key}: {cur:g} is {abs(cur - med) / sd:.1f} "
                    f"stdevs from history median {med:g} "
                    f"(stdev {sd:g}, n={len(traj)})")

    return {
        "status": status,
        "window": len(recent),
        "regressions": regressions,
        "variance_spikes": spikes,
        "trends": trends,
        "flags": ([f"regression: {m}" for m in regressions] +
                  [f"variance spike: {m}" for m in spikes]),
    }


def to_markdown(report: dict, platform: str = "?") -> str:
    """Render an :func:`analyze` report as a small markdown document."""
    lines = [f"# Bench watchdog ({platform})", ""]
    lines.append(f"History status: **{report['status']}** "
                 f"(guard window {report['window']})")
    lines.append("")
    for title, items in (("Regressions", report["regressions"]),
                         ("Variance spikes",
                          report["variance_spikes"])):
        lines.append(f"## {title}")
        if items:
            lines.extend(f"- {m}" for m in items)
        else:
            lines.append("- none")
        lines.append("")
    lines.append("## Trends")
    lines.append("")
    lines.append("| metric | direction | history | baseline | current |")
    lines.append("|---|---|---|---|---|")
    for key, t in sorted(report["trends"].items()):
        hist = " ".join(f"{v:g}" for v in t["history"]) or "-"
        base = t.get("baseline")
        lines.append(
            f"| {key} | {t['direction']} | {hist} | "
            f"{base if base is not None else '-'} | "
            f"{t['current'] if t['current'] is not None else '-'} |")
    lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--platform", default=None,
                    help="restrict to one platform tag (cpu/tpu)")
    ap.add_argument("--slip", type=float, default=0.20)
    args = ap.parse_args(argv)
    hist = load_history(args.dir, args.platform)
    if not hist:
        print(f"# Bench watchdog\n\nno BENCH_r*.json records under "
              f"{args.dir}")
        return 0
    tag, current = hist[-1]
    report = analyze(hist[:-1], current, slip=args.slip)
    platform = current.get("platform", args.platform or "?")
    print(to_markdown(report, platform=f"{platform}, newest r{tag}"))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
