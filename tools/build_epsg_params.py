"""Build mosaic_tpu/core/geometry/epsg_params.npz from the system PROJ
database (/usr/share/proj/proj.db, stdlib sqlite3 — no pyproj).

Reference counterpart: the reference delegates arbitrary-CRS transforms
to proj4j (MosaicGeometry.scala:136-160) / OSR (RasterProject.scala:45),
both of which carry the same EPSG registry this table is derived from.
Here the registry is baked into a compact npz resource and the
projection MATH is implemented in crs.py (EPSG Guidance Note 7-2
formulas) — no native proj dependency at runtime.

Extracted per EPSG projected CRS (non-deprecated, supported method):
  method code, projection parameters (normalized to degrees / metres /
  unity scale), axis unit->metre factor, ellipsoid (a, 1/f), prime
  meridian offset (deg), best direct Helmert->WGS84 (7 params + a
  validity flag; identity for WGS84-family and missing cases).
"""

import os
import sqlite3

import numpy as np

DB = "/usr/share/proj/proj.db"
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mosaic_tpu", "core", "geometry",
    "epsg_params.npz")

# EPSG method codes implemented in crs.py's generic engine
SUPPORTED = {
    9807,   # Transverse Mercator
    9808,   # Transverse Mercator (South Orientated)
    9801,   # Lambert Conic Conformal (1SP)
    9802,   # Lambert Conic Conformal (2SP)
    9822,   # Albers Equal Area
    9804,   # Mercator (variant A)
    9805,   # Mercator (variant B)
    9810,   # Polar Stereographic (variant A)
    9829,   # Polar Stereographic (variant B)
    9809,   # Oblique Stereographic
    9820,   # Lambert Azimuthal Equal Area
    9806,   # Cassini-Soldner
    9812,   # Hotine Oblique Mercator (variant A)
    9815,   # Hotine Oblique Mercator (variant B)
    9826,   # Lambert Conic Conformal (West Orientated)
}

# parameter slot layout in the packed table (NaN = absent)
#   0 lat0   1 lon0   2 sp1   3 sp2   4 k0   5 fe   6 fn
PARAM_SLOT = {
    8801: 0, 8821: 0,          # latitude of natural/false origin
    8802: 1, 8822: 1,          # longitude of natural/false origin
    8823: 2, 8832: 2,          # std parallel 1 / ps-B std parallel
    8824: 3,                   # std parallel 2
    8805: 4,                   # scale factor at natural origin
    8806: 5, 8826: 5,          # false easting
    8807: 6, 8827: 6,          # false northing
    8833: 1,                   # ps-B longitude of origin
    8811: 0, 8812: 1,          # HOM projection-centre lat/lon
    8813: 2,                   # HOM azimuth at centre
    8814: 3,                   # HOM rectified-to-skew grid angle
    8815: 4,                   # HOM scale factor on the initial line
    8816: 5, 8817: 6,          # HOM variant-B centre easting/northing
}


def dms_to_deg(v: float) -> float:
    """EPSG 9110 sexagesimal DD.MMSSsss -> decimal degrees."""
    sign = -1.0 if v < 0 else 1.0
    v = abs(v)
    d = int(v)
    rem = (v - d) * 100.0
    m = int(rem + 1e-9)
    s = (rem - m) * 100.0
    return sign * (d + m / 60.0 + s / 3600.0)


def main():
    db = sqlite3.connect(DB)
    cur = db.cursor()
    uom = {code: (name, typ, conv) for code, name, typ, conv in
           cur.execute("SELECT code, name, type, conv_factor "
                       "FROM unit_of_measure WHERE auth_name='EPSG'")}

    def angle_deg(value, uom_code):
        if value is None:
            return np.nan
        if uom_code == 9110:
            return dms_to_deg(value)
        name, typ, conv = uom[uom_code]
        # conv is radians per unit for angles
        return np.degrees(value * conv)

    def length_m(value, uom_code):
        if value is None:
            return np.nan
        return value * uom[uom_code][2]

    def scale_unity(value, uom_code):
        if value is None:
            return np.nan
        return value * uom[uom_code][2]

    ell = {code: (a, rf, b) for code, a, rf, b in cur.execute(
        "SELECT code, semi_major_axis, inv_flattening, semi_minor_axis "
        "FROM ellipsoid WHERE auth_name='EPSG'")}
    pm = {code: angle_deg(lon, u) for code, lon, u in cur.execute(
        "SELECT code, longitude, uom_code FROM prime_meridian "
        "WHERE auth_name='EPSG'")}
    datum = {code: (e, p) for code, e, p in cur.execute(
        "SELECT code, ellipsoid_code, prime_meridian_code "
        "FROM geodetic_datum WHERE auth_name='EPSG'")}
    geod = {code: d for code, d in cur.execute(
        "SELECT code, datum_code FROM geodetic_crs "
        "WHERE auth_name='EPSG'")}

    # best direct Helmert to WGS84 per source geodetic CRS
    helm = {}
    for (src, tx, ty, tz, rx, ry, rz, sc, acc, mcode,
         t_u, r_u, sc_u) in cur.execute(
            "SELECT source_crs_code, tx, ty, tz, rx, ry, rz, "
            "scale_difference, accuracy, method_code, "
            "translation_uom_code, rotation_uom_code, "
            "scale_difference_uom_code "
            "FROM helmert_transformation "
            "WHERE auth_name='EPSG' AND deprecated=0 "
            "AND target_crs_auth_name='EPSG' AND target_crs_code=4326 "
            "AND method_code IN (9603, 9606, 9607)"):
        acc = 999.0 if acc is None else float(acc)
        prev = helm.get(src)
        if prev is not None and prev[-1] <= acc:
            continue

        def lin(v):
            return 0.0 if v is None else v * uom[t_u][2]

        def rot(v):
            # rotations stored in angle units -> arcseconds
            if v is None or r_u is None:
                return 0.0
            return np.degrees(v * uom[r_u][2]) * 3600.0
        rxs, rys, rzs = rot(rx), rot(ry), rot(rz)
        if mcode == 9607:      # coordinate frame -> position vector
            rxs, rys, rzs = -rxs, -rys, -rzs
        sc_ppm = 0.0 if sc is None else sc * uom[sc_u][2] * 1e6
        helm[src] = (lin(tx), lin(ty), lin(tz),
                     rxs, rys, rzs, sc_ppm, acc)

    # axis unit per coordinate system (require uniform east/north-ish)
    cs_unit = {}
    for cs, u, orient in cur.execute(
            "SELECT coordinate_system_code, uom_code, orientation "
            "FROM axis WHERE coordinate_system_auth_name='EPSG'"):
        cs_unit.setdefault(cs, []).append((u, orient))

    rows = []
    skipped = {}
    q = """
    SELECT p.code, c.method_code, p.coordinate_system_code,
           p.geodetic_crs_code, p.name,
           c.param1_code, c.param1_value, c.param1_uom_code,
           c.param2_code, c.param2_value, c.param2_uom_code,
           c.param3_code, c.param3_value, c.param3_uom_code,
           c.param4_code, c.param4_value, c.param4_uom_code,
           c.param5_code, c.param5_value, c.param5_uom_code,
           c.param6_code, c.param6_value, c.param6_uom_code,
           c.param7_code, c.param7_value, c.param7_uom_code
    FROM projected_crs p
    JOIN conversion c ON c.auth_name = p.conversion_auth_name
                     AND c.code = p.conversion_code
    WHERE p.auth_name='EPSG' AND p.deprecated=0
    """
    for row in cur.execute(q):
        code, method, cs, gcrs, name = row[:5]
        if method not in SUPPORTED:
            skipped[method] = skipped.get(method, 0) + 1
            continue
        axes = cs_unit.get(cs, [])
        units = {u for u, _ in axes}
        orients = {o for _, o in axes}
        if len(units) != 1:
            continue
        if method == 9808:
            ok = orients <= {"south", "west"}     # TM-SO's own axes
        elif method == 9826:
            ok = orients <= {"west", "north"}     # LCC-W westing axis
        elif method in (9810, 9829):
            # polar axes read "North along 90°E" etc — that IS the
            # standard polar (E,N) frame the 9810/9829 formulas use
            ok = True
        else:
            ok = orients <= {"east", "north"}
        if not ok:
            continue
        axis_m = uom[next(iter(units))][2]
        dcode = geod.get(gcrs)
        if dcode is None or dcode not in datum:
            continue
        ecode, pmcode = datum[dcode]
        a, rf, b = ell.get(ecode, (np.nan, None, None))
        if rf is None:
            rf = a / (a - b) if b not in (None, a) else np.inf
        p7 = np.full(7, np.nan)
        for k in range(7):
            pcode, pval, puom = row[5 + 3 * k: 8 + 3 * k]
            if pcode is None or pcode not in PARAM_SLOT:
                continue
            slot = PARAM_SLOT[pcode]
            typ = uom[puom][1]
            if typ == "angle":
                p7[slot] = angle_deg(pval, puom)
            elif typ == "length":
                p7[slot] = length_m(pval, puom)
            else:
                p7[slot] = scale_unity(pval, puom)
        h = helm.get(gcrs)
        wgs_family = gcrs in (4326, 4979, 4978)
        if h is None:
            hp = np.zeros(7)
            hacc = 0.0 if wgs_family else np.nan
        else:
            hp = np.array(h[:7])
            hacc = h[7]
        rows.append((int(code), int(method), p7, axis_m, a, rf,
                     pm.get(pmcode, 0.0), hp, hacc, name))

    rows.sort(key=lambda r: r[0])
    epsg = np.array([r[0] for r in rows], np.int32)
    method = np.array([r[1] for r in rows], np.int16)
    params = np.stack([r[2] for r in rows])
    axis_m = np.array([r[3] for r in rows])
    ell_a = np.array([r[4] for r in rows])
    ell_rf = np.array([r[5] for r in rows])
    pm_deg = np.array([r[6] for r in rows])
    helmert = np.stack([r[7] for r in rows])
    helmert_acc = np.array([r[8] for r in rows])
    # normalized CRS names (for ESRI .prj files that carry no EPSG
    # AUTHORITY: match on the PROJCS name instead)
    import re as _re
    names = np.array([_re.sub(r"[^A-Z0-9]+", "_",
                                r[9].upper()).strip("_")
                      for r in rows])
    # ESRI/other alias names -> EPSG code (for .prj files that use
    # ESRI naming and carry no AUTHORITY node)
    keep = set(int(c) for c in epsg)
    al_names, al_codes = [], []
    for tn, code, alt in cur.execute(
            "SELECT table_name, code, alt_name FROM alias_name "
            "WHERE auth_name='EPSG'"):
        if tn == "projected_crs" and int(code) in keep:
            al_names.append(_re.sub(r"[^A-Z0-9]+", "_",
                                    alt.upper()).strip("_"))
            al_codes.append(int(code))
    np.savez_compressed(OUT, epsg=epsg, method=method, params=params,
                        axis_m=axis_m, ell_a=ell_a, ell_rf=ell_rf,
                        pm_deg=pm_deg, helmert=helmert,
                        helmert_acc=helmert_acc, name=names,
                        alias_name=np.array(al_names),
                        alias_code=np.array(al_codes, np.int32))
    print(f"wrote {len(rows)} EPSG projected CRSs -> {OUT}")
    print("skipped methods:", dict(sorted(skipped.items(),
                                          key=lambda kv: -kv[1])[:8]))


if __name__ == "__main__":
    main()
