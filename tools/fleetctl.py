#!/usr/bin/env python
"""fleetctl: inspect a mosaic_tpu fleet telemetry spool directory.

Every worker process spools its telemetry under ``mosaic.obs.fleet.
dir`` (see ``mosaic_tpu/obs/spool.py``); this CLI runs the
:class:`~mosaic_tpu.obs.fleet.FleetAggregator` over that directory
from the OUTSIDE — an operator shell, a cron probe, a CI assert — so
fleet state is inspectable without attaching to any worker.

    python tools/fleetctl.py list    --dir /tmp/fleet
    python tools/fleetctl.py alerts  --dir /tmp/fleet
    python tools/fleetctl.py metrics --dir /tmp/fleet
    python tools/fleetctl.py traces  --dir /tmp/fleet
    python tools/fleetctl.py bundle  --dir /tmp/fleet --out fleet.json

* ``list``    — one line per worker: pid, spool age, fresh/STALE, any
  read error (torn spool, alien version).
* ``alerts``  — merged per-worker active SLO alerts plus the fleet-
  level burn-rate evaluation over the merged series.
* ``metrics`` — the worker-labeled OpenMetrics exposition of the
  merged view (counters/gauges per worker, histograms exactly merged).
* ``traces``  — stitched cross-process traces: every W3C trace id the
  fleet served, which workers took part, and their spans.
* ``bundle``  — the full fleet bundle as JSON (to ``--out`` or
  stdout): merged view + fleet SLO + stitched traces + every worker's
  recent flight-recorder events.

``--dir`` defaults to the configured ``mosaic.obs.fleet.dir`` (env
``MOSAIC_TPU_FLEET_DIR`` overrides for shells with no conf).  Exit
code 1 when the directory has no readable spools at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _resolve_dir(arg: Optional[str]) -> str:
    if arg:
        return arg
    env = os.environ.get("MOSAIC_TPU_FLEET_DIR", "").strip()
    if env:
        return env
    from mosaic_tpu import config as _config
    return _config.default_config().obs_fleet_dir


def cmd_list(agg, view, args) -> int:
    for w in view.workers:
        state = "STALE" if w.stale else "fresh"
        err = f"  [{w.error}]" if w.error else ""
        print(f"worker {w.pid:>7}  age {w.age_s:7.2f}s  "
              f"{state}{err}")
    print(f"{len(view.workers)} workers, "
          f"{sum(1 for w in view.workers if w.stale)} stale, "
          f"{view.merge_errors} merge errors")
    return 0


def cmd_alerts(agg, view, args) -> int:
    out = {"active": view.slo_active,
           "breaches": view.slo_breaches,
           "fleet": [r for r in agg.evaluate_slo(view)
                     if args.all or r.get("breached")]}
    json.dump(out, sys.stdout, indent=2, default=str)
    print()
    return 0


def cmd_metrics(agg, view, args) -> int:
    from mosaic_tpu.obs.openmetrics import fleet_to_openmetrics
    sys.stdout.write(fleet_to_openmetrics(view))
    return 0


def cmd_traces(agg, view, args) -> int:
    json.dump(agg.stitched_traces(view), sys.stdout, indent=2,
              default=str)
    print()
    return 0


def cmd_bundle(agg, view, args) -> int:
    bundle = agg.bundle(view)
    if args.out:
        tmp = f"{args.out}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(bundle, fh, default=str)
        os.replace(tmp, args.out)
        print(f"fleet bundle -> {args.out}")
    else:
        json.dump(bundle, sys.stdout, indent=2, default=str)
        print()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleetctl", description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="spool directory (default: configured "
                         "mosaic.obs.fleet.dir / MOSAIC_TPU_FLEET_DIR)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="workers + freshness")
    p = sub.add_parser("alerts", help="merged + fleet-level alerts")
    p.add_argument("--all", action="store_true",
                   help="include non-breaching fleet objectives")
    sub.add_parser("metrics", help="worker-labeled OpenMetrics")
    sub.add_parser("traces", help="stitched cross-process traces")
    p = sub.add_parser("bundle", help="dump the fleet bundle")
    p.add_argument("--out", default=None,
                   help="write JSON here (default: stdout)")
    args = ap.parse_args(argv)

    directory = _resolve_dir(args.dir)
    if not directory:
        print("fleetctl: no spool dir (--dir, MOSAIC_TPU_FLEET_DIR, "
              "or SET mosaic.obs.fleet.dir)", file=sys.stderr)
        return 2
    from mosaic_tpu.obs.fleet import aggregator_for
    agg = aggregator_for(directory)
    view = agg.scan()
    handler = {"list": cmd_list, "alerts": cmd_alerts,
               "metrics": cmd_metrics, "traces": cmd_traces,
               "bundle": cmd_bundle}[args.cmd]
    rc = handler(agg, view, args)
    if rc == 0 and not any(w.readable for w in view.workers):
        print(f"fleetctl: no readable spools under {directory}",
              file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
