#!/usr/bin/env python3
"""graftlint CLI: run the mosaic_tpu static-analysis rules.

Usage (from the repo root):

    python tools/graftlint.py --check          # CI gate: exit 0/1
    python tools/graftlint.py --json           # machine output
    python tools/graftlint.py --rules jit-raw-jit,lock-unguarded-attr
    python tools/graftlint.py --changed        # findings in the diff
    python tools/graftlint.py --changed origin/main   # ...vs a ref
    python tools/graftlint.py --sarif out.sarif  # PR-annotation output
    python tools/graftlint.py --list-rules     # rule catalogue
    python tools/graftlint.py --update-baseline  # rewrite baseline

Exit codes: 0 clean (every finding suppressed or baselined), 1 new
findings (or stale baseline entries under --check), 2 tool error
(corrupt baseline, bad arguments).

See docs/usage/linting.md for the rule catalogue and the
suppression/baseline workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)


def _import_lint():
    """Load mosaic_tpu.lint WITHOUT importing mosaic_tpu: the package
    __init__ pulls jax (~0.4 s), which the pure-stdlib linter never
    touches — skipping it keeps ``--changed`` pre-commit runs inside
    their latency budget.  The lint package only uses relative imports
    internally, so it loads cleanly under a private name."""
    import importlib.util
    pkg = os.path.join(_ROOT, "mosaic_tpu", "lint")
    spec = importlib.util.spec_from_file_location(
        "_graftlint_rules", os.path.join(pkg, "__init__.py"),
        submodule_search_locations=[pkg])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint_rules"] = mod
    spec.loader.exec_module(mod)
    return mod


lint = _import_lint()

DEFAULT_BASELINE = os.path.join("tools", "graftlint_baseline.json")


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=_ROOT,
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "under --root)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run "
                         "(default: all)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON object instead of text")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: also fail (exit 1) on stale "
                         "baseline entries")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to cover current "
                         "findings (reasons carry over; new entries "
                         "get a TODO reason to fill in)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print grandfathered findings")
    ap.add_argument("--changed", nargs="?", const="HEAD",
                    default=None, metavar="REF",
                    help="report only findings anchored in files "
                         "changed vs REF (default HEAD: working-tree "
                         "diff + untracked).  Every rule still sees "
                         "the whole repo — graph and cross-file rules "
                         "need it — so this scopes the REPORT, not "
                         "the analysis; stale-baseline noise is "
                         "suppressed")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write findings as SARIF 2.1.0 to PATH "
                         "(CI uploads it so findings annotate the PR "
                         "diff)")
    return ap.parse_args(argv)


def _changed_paths(root: str, ref: str):
    """Repo-relative paths changed vs ``ref`` plus untracked files;
    None when git is unavailable (caller falls back to a full
    report)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", ref, "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
        extra = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    paths = set()
    for out in (diff.stdout, extra.stdout if extra.returncode == 0
                else ""):
        paths.update(p.strip() for p in out.splitlines() if p.strip())
    return paths


def _sarif(findings, rules) -> dict:
    """Minimal SARIF 2.1.0: one run, one result per NEW finding —
    enough for GitHub code-scanning upload to pin findings to diff
    lines."""
    by_id = {r.id: r for r in rules}
    used = sorted({f.rule for f in findings})
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/usage/linting.md",
                "rules": [{
                    "id": rid,
                    "shortDescription": {"text": by_id[rid].doc
                                         if rid in by_id else rid},
                } for rid in used],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(1, f.line)},
                    },
                }],
            } for f in findings],
        }],
    }


def main(argv=None) -> int:
    args = _parse_args(argv if argv is not None else sys.argv[1:])

    if args.list_rules:
        fam = None
        for r in sorted(lint.all_rules(),
                        key=lambda r: (r.family, r.id)):
            if r.family != fam:
                fam = r.family
                print(f"[{fam}]")
            print(f"  {r.id:28s} {r.doc}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [s.strip() for s in args.rules.split(",")
                    if s.strip()]
        known = {r.id for r in lint.all_rules()}
        bad = sorted(set(rule_ids) - known)
        if bad:
            print(f"graftlint: unknown rule(s): {', '.join(bad)}",
                  file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(args.root,
                                                  DEFAULT_BASELINE)
    try:
        baseline = lint.load_baseline(baseline_path)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    repo = lint.Repo.from_root(args.root)
    changed = None
    if args.changed is not None:
        changed = _changed_paths(args.root, args.changed)
        if changed is not None:
            # graph and cross-file collection passes still see the
            # whole repo; per-module walks and the REPORT are scoped
            # to the diff.  Stale entries are a full-run concern, not
            # a pre-commit one.
            repo.focus_paths = changed
        else:
            print("graftlint: --changed: git diff failed; reporting "
                  "the full repo", file=sys.stderr)
    findings = lint.run_lint(repo, rule_ids)
    new, grandfathered, stale = lint.apply_baseline(findings, baseline)
    if changed is not None:
        stale = []

    if args.sarif:
        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(_sarif(new, lint.all_rules()), fh, indent=2)
            fh.write("\n")

    if args.update_baseline:
        data = lint.baseline_from_findings(findings,
                                           previous=baseline)
        os.makedirs(os.path.dirname(os.path.abspath(baseline_path)),
                    exist_ok=True)
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"graftlint: baseline rewritten with "
              f"{len(data['findings'])} entr"
              f"{'y' if len(data['findings']) == 1 else 'ies'} "
              f"-> {baseline_path}")
        todo = [k for k, v in data["findings"].items()
                if str(v["reason"]).startswith("TODO")]
        if todo:
            print(f"graftlint: {len(todo)} entries need a reason "
                  "before committing:")
            for k in todo:
                print(f"  {k}")
        return 0

    if args.json:
        out = {
            "version": 1,
            "counts": {"new": len(new),
                       "baselined": len(grandfathered),
                       "stale_baseline": len(stale)},
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in grandfathered],
            "stale_baseline": stale,
        }
        json.dump(out, sys.stdout, indent=2)
        print()
    else:
        for f in new:
            print(f.render())
        if args.show_baselined:
            for f in grandfathered:
                print(f"{f.render()}  [baselined]")
        for key in stale:
            print(f"stale baseline entry (debt paid — prune with "
                  f"--update-baseline): {key}")
        n, b, s = len(new), len(grandfathered), len(stale)
        print(f"graftlint: {n} finding{'s' if n != 1 else ''}, "
              f"{b} baselined, {s} stale baseline "
              f"entr{'y' if s == 1 else 'ies'}")

    if new:
        return 1
    if args.check and stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
