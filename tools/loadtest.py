#!/usr/bin/env python
"""Load generator for the mosaic_tpu query server (serve/).

Importable (bench.py's ``serving`` record block and the CI smoke lane
call :func:`run_loadtest` / :func:`deadline_curve` in-process) and a
CLI::

    python tools/loadtest.py --url http://127.0.0.1:8817 \
        --clients 8 --duration 3 --sql "SELECT count(*) FROM pts"

N concurrent closed-loop clients (one thread + one keep-alive-free
HTTP connection each) replay a weighted query mix against ``POST
/query``; client-observed latency lands in the repo's own metrics
histograms (``serve/client_ms`` — the same reservoir machinery every
other percentile in the codebase uses), so the report's p50/p95/p99
are computed by ``obs.metrics``, not by this script.  Outcomes are
bucketed by HTTP status: ok (200), denied (429 admission), shed
(429 with reason=shed), deadline (504), cancelled (499), error.

Fleet mode (``--fleet`` / ``failover=True``): connects retry with
jittered backoff through ``resilience.retry.LOADTEST_CONNECT_RETRY``
(bounded — a down fleet still fails), and a request that dies with a
torn connection mid-flight is retried ONCE on a fresh connection —
against a ``ServeFleet`` the kernel routes the retry to a surviving
worker, so a SIGKILLed worker costs latency, not answers.  The
summary reports ``connect_retries`` and ``failovers`` separately from
the ``lost`` outcome bucket (dead even after the retry), so a kill
drill distinguishes lost-forever from retried-ok.

:func:`deadline_curve` sweeps offered QPS (open-loop pacing) under a
fixed per-request deadline and reports the deadline-miss fraction at
each level — the knee of that curve is the server's sustainable
throughput for an SLO.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_HIST = "serve/client_ms"


def _w3c_traceparent(rng) -> str:
    """A fresh W3C traceparent from the client's RNG (all-zero ids
    are invalid per spec, so re-roll the astronomically unlikely)."""
    trace = rng.getrandbits(128) or 1
    span = rng.getrandbits(64) or 1
    return f"00-{trace:032x}-{span:016x}-01"


class ClientCounters:
    """Thread-safe tally shared by every client thread: connect
    retries, mid-flight failovers — the kill drill's evidence that
    requests were retried-ok rather than lost."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, int] = {}

    def bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._data[key] = self._data.get(key, 0) + n

    def get(self, key: str) -> int:
        with self._lock:
            return self._data.get(key, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._data)


def _connect(host: str, port: int, timeout: float,
             counters: Optional[ClientCounters]):
    """A connected HTTPConnection, retrying refused/reset connects
    with the bounded jittered-backoff policy (a fleet worker dying
    between accept queues surfaces here)."""
    import http.client
    from mosaic_tpu.resilience.retry import LOADTEST_CONNECT_RETRY

    def attempt():
        c = http.client.HTTPConnection(host, port, timeout=timeout)
        c.connect()
        return c

    def on_retry(exc, n):
        if counters is not None:
            counters.bump("connect_retries")

    return LOADTEST_CONNECT_RETRY.call(attempt, on_retry=on_retry)


def _post_query(host: str, port: int, sql: str, principal: str,
                priority: int = 0, deadline_ms: float = 0.0,
                timeout: float = 30.0,
                traceparent: Optional[str] = None,
                counters: Optional[ClientCounters] = None,
                failover: bool = False) -> Tuple[int, str]:
    """One POST /query on a fresh connection; returns (status,
    reason) where reason is the deny reason for 429s, "" otherwise.
    ``failover=True`` retries a torn-connection request exactly once
    on a fresh connection (queries are read-only — safe to replay);
    the second failure propagates to the caller as lost."""

    def attempt() -> Tuple[int, str]:
        conn = _connect(host, port, timeout, counters)
        try:
            headers = {"X-Mosaic-Principal": principal,
                       "Content-Type": "text/plain"}
            if priority:
                headers["X-Mosaic-Priority"] = str(priority)
            if deadline_ms > 0:
                headers["X-Mosaic-Deadline-Ms"] = str(deadline_ms)
            if traceparent:
                headers["traceparent"] = traceparent
            conn.request("POST", "/query", body=sql.encode(),
                         headers=headers)
            resp = conn.getresponse()
            body = resp.read()
            reason = ""
            if resp.status in (429, 503):
                try:
                    reason = json.loads(body).get("reason", "")
                except Exception:
                    pass
            return resp.status, reason
        finally:
            conn.close()

    try:
        return attempt()
    except Exception:
        if not failover:
            raise
        if counters is not None:
            counters.bump("failovers")
        return attempt()


def _bucket(status: int, reason: str) -> str:
    if status == 200:
        return "ok"
    if status == 429:
        return "shed" if reason == "shed" else "denied"
    if status == 504:
        return "deadline"
    if status == 499:
        return "cancelled"
    if status == 503:
        return "denied"
    return "error"


def run_loadtest(host: str, port: int,
                 mix: Sequence[Tuple[str, float]],
                 clients: int = 8,
                 duration_s: float = 3.0,
                 principals: Optional[Sequence[str]] = None,
                 deadline_ms: float = 0.0,
                 priority_of: Optional[Dict[str, int]] = None,
                 failover: bool = False
                 ) -> Dict[str, object]:
    """Closed-loop burst: ``clients`` threads each loop pick-query →
    POST → record for ``duration_s``.  ``mix`` is ``[(sql, weight)]``;
    clients are assigned principals round-robin from ``principals``
    (default: one shared "loadtest" tenant).  ``failover=True`` is
    fleet mode: torn requests retry once against surviving workers.
    Returns the aggregate report (see module docstring)."""
    from mosaic_tpu.obs import metrics
    from mosaic_tpu.obs.context import link_traceparent, new_trace
    from mosaic_tpu.obs.tracer import tracer
    tracer.enable()               # client spans must exist to stitch
    metrics.enable()
    principals = list(principals or ["loadtest"])
    priority_of = priority_of or {}
    weights = [max(0.0, w) for _, w in mix]
    total_w = sum(weights) or 1.0
    cum, acc = [], 0.0
    for w in weights:
        acc += w / total_w
        cum.append(acc)
    lock = threading.Lock()
    outcomes: Dict[str, int] = {}
    by_principal: Dict[str, Dict[str, int]] = {}
    counters = ClientCounters()
    lat_key = f"{_HIST}@{time.monotonic_ns()}"  # fresh reservoir per run

    def pick(r: float) -> str:
        for (sql, _), edge in zip(mix, cum):
            if r <= edge:
                return sql
        return mix[-1][0]

    def client(idx: int) -> None:
        import random
        rng = random.Random(1_000 + idx)
        principal = principals[idx % len(principals)]
        prio = priority_of.get(principal, 0)
        t_end = time.perf_counter() + duration_s
        while time.perf_counter() < t_end:
            sql = pick(rng.random())
            # every request carries a fresh W3C traceparent, and the
            # client's own trace links to the SAME id — the server
            # worker links its query trace to it too, so both sides'
            # spans stitch into one cross-process tree in the fleet
            # bundle (fleet.stitched_traces)
            tp = _w3c_traceparent(rng)
            t0 = time.perf_counter()
            lost = False
            try:
                with link_traceparent(tp), \
                        new_trace(f"client:{principal}"):
                    with tracer.span("loadtest/request"):
                        status, reason = _post_query(
                            host, port, sql, principal, priority=prio,
                            deadline_ms=deadline_ms, traceparent=tp,
                            counters=counters, failover=failover)
            except Exception:
                # no answer even after the failover retry (or failover
                # off): this request is gone for good
                status, reason, lost = -1, "", True
            dt_ms = (time.perf_counter() - t0) * 1e3
            b = "lost" if lost else _bucket(status, reason)
            if b == "ok":
                metrics.observe(lat_key, dt_ms)
            with lock:
                outcomes[b] = outcomes.get(b, 0) + 1
                per = by_principal.setdefault(principal, {})
                per[b] = per.get(b, 0) + 1
            if b in ("denied", "shed"):
                time.sleep(0.01)     # honor the 429 a little

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 30.0)
    wall = time.perf_counter() - t0
    snap = metrics.report().get("histograms", {}).get(lat_key, {})
    n = sum(outcomes.values())
    answered = n - outcomes.get("lost", 0)
    return {
        "clients": clients,
        "duration_s": round(wall, 3),
        "requests": n,
        "qps": round(n / max(1e-9, wall), 1),
        "ok_qps": round(outcomes.get("ok", 0) / max(1e-9, wall), 1),
        "outcomes": dict(sorted(outcomes.items())),
        # answered / sent: every request the server answered (ok,
        # denied, shed, ... — an honest 429 is availability, a torn
        # socket with no retry success is not)
        "availability": round(answered / max(1, n), 4),
        "connect_retries": counters.get("connect_retries"),
        "failovers": counters.get("failovers"),
        "lost": outcomes.get("lost", 0),
        "by_principal": {p: dict(sorted(v.items()))
                         for p, v in sorted(by_principal.items())},
        "latency_ms": {k: snap.get(k) for k in
                       ("count", "mean", "p50", "p95", "p99", "max")},
    }


def deadline_curve(host: str, port: int, sql: str,
                   deadline_ms: float,
                   qps_levels: Sequence[float] = (2, 5, 10, 20, 40),
                   duration_s: float = 2.0,
                   principal: str = "loadtest"
                   ) -> List[Dict[str, object]]:
    """QPS-vs-deadline-miss curve: open-loop paced offers at each
    level; a miss is any request that did not come back 200 within
    the deadline (504s, denies, sheds all count — the client asked
    and the answer wasn't the data in time)."""
    curve: List[Dict[str, object]] = []
    for qps in qps_levels:
        period = 1.0 / float(qps)
        results: List[str] = []
        lock = threading.Lock()
        threads: List[threading.Thread] = []

        def fire() -> None:
            try:
                status, reason = _post_query(
                    host, port, sql, principal,
                    deadline_ms=deadline_ms,
                    timeout=deadline_ms / 1e3 + 5.0)
            except Exception:
                status, reason = -1, ""
            with lock:
                results.append(_bucket(status, reason))

        t_end = time.perf_counter() + duration_s
        nxt = time.perf_counter()
        while time.perf_counter() < t_end:
            th = threading.Thread(target=fire, daemon=True)
            th.start()
            threads.append(th)
            nxt += period
            lag = nxt - time.perf_counter()
            if lag > 0:
                time.sleep(lag)
        for th in threads:
            th.join(deadline_ms / 1e3 + 10.0)
        n = len(results)
        miss = sum(1 for b in results if b != "ok")
        curve.append({"offered_qps": float(qps),
                      "requests": n,
                      "miss": miss,
                      "miss_frac": round(miss / max(1, n), 4)})
    return curve


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="server base url, e.g. http://127.0.0.1:8817")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--sql", action="append", required=True,
                    help="query to replay (repeat for a mix; "
                         "'WEIGHT:SQL' to weight)")
    ap.add_argument("--principal", action="append", default=None,
                    help="tenant name (repeat; clients round-robin)")
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--fleet", action="store_true",
                    help="fleet mode: retry a torn-connection request "
                         "once against surviving workers (failover)")
    ap.add_argument("--curve", action="store_true",
                    help="also sweep the QPS-vs-deadline-miss curve "
                         "(first --sql, needs --deadline-ms)")
    args = ap.parse_args(argv)
    from urllib.parse import urlparse
    u = urlparse(args.url)
    host, port = u.hostname or "127.0.0.1", u.port or 80
    mix: List[Tuple[str, float]] = []
    for s in args.sql:
        if ":" in s and s.split(":", 1)[0].replace(".", "").isdigit():
            w, q = s.split(":", 1)
            mix.append((q, float(w)))
        else:
            mix.append((s, 1.0))
    report = run_loadtest(host, port, mix, clients=args.clients,
                          duration_s=args.duration,
                          principals=args.principal,
                          deadline_ms=args.deadline_ms,
                          failover=args.fleet)
    if args.curve and args.deadline_ms > 0:
        report["deadline_curve"] = deadline_curve(
            host, port, mix[0][0], args.deadline_ms)
    json.dump(report, sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
