#!/usr/bin/env python
"""mosaicstat: analyze a mosaic_tpu workload history directory.

Every worker with ``mosaic.history.dir`` set writes one durable
record per completed query (see ``mosaic_tpu/obs/history.py``); this
CLI reads that directory — raw segments and compacted summaries alike
— from the OUTSIDE, so workload analysis needs no running worker.

    python tools/mosaicstat.py top        --dir /tmp/hist
    python tools/mosaicstat.py principals --dir /tmp/hist
    python tools/mosaicstat.py strategies --dir /tmp/hist
    python tools/mosaicstat.py heatmap    --dir /tmp/hist --top 20
    python tools/mosaicstat.py diff       --dir /tmp/hist --json
    python tools/mosaicstat.py layout     --store /tmp/store
    python tools/mosaicstat.py report     --dir /tmp/hist

* ``top``        — the costliest raw-record queries by ``--by``
  (wall_ms by default; any cost-vector field works), outcome-tagged.
* ``principals`` — per-principal totals over every window: queries,
  wall, device seconds, rows, transfer bytes, compiles.
* ``strategies`` — planner strategy win rates per decision point
  (how often each choice was taken, forced picks split out) plus the
  window's mispredict count.
* ``heatmap``    — partition heat from the stored records: rows/bytes
  per store cell, hottest first, with the hot/cold skew ratio.
* ``diff``       — window-over-window regression check on the two
  most recent windows: per-operator p50/p95 slips, flagged past the
  20% threshold (exit code 3 when anything is flagged, so a CI lane
  can gate on it).  ``--json`` emits the machine-readable verdict.
* ``layout``     — the learned store-layout recommendation
  (``mosaic_tpu.sql.layout.advise_layout``): grid res + shard rows
  from an existing store's manifest (``--store``) plus whatever heat
  and history evidence the history dirs contribute.
* ``report``     — the full merged JSON report (all windows + totals).

``--dir`` defaults to ``MOSAIC_TPU_HISTORY_DIR`` then the configured
``mosaic.history.dir``; pass ``--dir`` more than once to merge
several workers' histories fleet-wide (exact merge — percentiles
come from summed buckets, never averaged).  ``--window-ms`` re-windows
raw records without touching on-disk summaries.  Exit code 1 when the
directory holds no records at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def _resolve_dirs(args) -> list:
    if args.dir:
        return list(args.dir)
    env = os.environ.get("MOSAIC_TPU_HISTORY_DIR", "").strip()
    if env:
        return [env]
    from mosaic_tpu import config as _config
    d = _config.default_config().history_dir
    return [d] if d else []


def _merged(dirs, window_ms):
    """One report dict over one or many history dirs."""
    if len(dirs) == 1:
        from mosaic_tpu.obs.history import report
        return report(dirs[0], window_ms)
    from mosaic_tpu.obs.fleet import merge_history
    return merge_history(dirs, window_ms)


def cmd_top(dirs, args) -> int:
    from mosaic_tpu.obs.history import load_records
    recs = []
    for d in dirs:
        recs.extend(load_records(d))
    if not recs:
        return 1
    by = args.by
    recs.sort(key=lambda r: -float((r.get("cost") or {}).get(by, 0)))
    print(f"{'query':<14} {'principal':<12} {'outcome':<10} "
          f"{by:>14}  sql")
    for r in recs[:args.top]:
        cost = r.get("cost") or {}
        sql = str(r.get("sql", ""))[:48]
        print(f"{str(r.get('query_id', '-')):<14} "
              f"{str(r.get('principal', '-')):<12} "
              f"{str(r.get('outcome', '-')):<10} "
              f"{float(cost.get(by, 0)):>14.3f}  {sql}")
    return 0


def cmd_principals(dirs, args) -> int:
    rep = _merged(dirs, args.window_ms)
    totals = rep["totals"]
    if not totals["queries"]:
        return 1
    print(f"{'principal':<16} {'queries':>8} {'wall_ms':>12} "
          f"{'device_s':>10} {'rows_out':>12} {'h2d_bytes':>14} "
          f"{'compiles':>9}")
    for p, t in totals["principals"].items():
        print(f"{p:<16} {t['queries']:>8} {t['wall_ms']:>12.1f} "
              f"{t['device_s']:>10.4f} {t['rows_out']:>12} "
              f"{t['h2d_bytes']:>14} {t['compiles']:>9}")
    return 0


def cmd_strategies(dirs, args) -> int:
    rep = _merged(dirs, args.window_ms)
    totals = rep["totals"]
    if not totals["queries"]:
        return 1
    strategies = totals.get("strategies", {})
    if not strategies:
        print("no planner strategy decisions recorded")
    for op, per in strategies.items():
        total = sum(per.values())
        print(f"{op} ({total} decisions)")
        for strat, n in sorted(per.items(), key=lambda kv: -kv[1]):
            print(f"  {strat:<40} {n:>7}  {100.0 * n / total:6.1f}%")
    print(f"mispredicts: {totals.get('mispredicts', 0)} over "
          f"{totals['queries']} queries")
    return 0


def cmd_heatmap(dirs, args) -> int:
    rep = _merged(dirs, args.window_ms)
    totals = rep["totals"]
    if not totals["queries"]:
        return 1
    parts = totals.get("partitions", {})
    if not parts:
        print("no partition accesses recorded")
        return 0
    rows = [r["rows"] for r in parts.values()]
    mean = sum(rows) / len(rows)
    skew = (max(rows) / mean) if mean else 0.0
    width = max(max(rows), 1)
    print(f"{len(parts)} partitions touched, hot/cold skew "
          f"{skew:.2f}x")
    print(f"{'cell':>8} {'queries':>8} {'rows':>12} {'bytes':>14}  "
          f"heat")
    for cell, v in list(parts.items())[:args.top]:
        bar = "#" * max(1, int(round(40.0 * v["rows"] / width))) \
            if v["rows"] else ""
        print(f"{cell:>8} {v['queries']:>8} {v['rows']:>12} "
              f"{v['bytes']:>14}  {bar}")
    return 0


def cmd_diff(dirs, args) -> int:
    from mosaic_tpu.obs.history import window_diff
    rep = _merged(dirs, args.window_ms)
    windows = rep["windows"]
    if len(windows) < 2:
        print(f"mosaicstat: need 2 windows to diff, have "
              f"{len(windows)}", file=sys.stderr)
        return 1
    prev, cur = windows[-2], windows[-1]
    verdict = window_diff(prev, cur)
    if args.json:
        json.dump(verdict, sys.stdout, indent=2, default=str)
        print()
    else:
        print(f"window {verdict['a']} ({verdict['a_queries']} q) -> "
              f"{verdict['b']} ({verdict['b_queries']} q), "
              f"threshold {verdict['threshold']:.0%}")
        for op, d in verdict["operators"].items():
            flag = "  << REGRESSION" if d["flagged"] else ""
            print(f"  {op:<20} p50 {d['a_p50_ms']:>9.3f} -> "
                  f"{d['b_p50_ms']:>9.3f} ms ({d['slip_p50']:+.1%})  "
                  f"p95 {d['a_p95_ms']:>9.3f} -> "
                  f"{d['b_p95_ms']:>9.3f} ms "
                  f"({d['slip_p95']:+.1%}){flag}")
        if verdict["flagged"]:
            print(f"FLAGGED: {', '.join(verdict['flagged'])}")
    return 3 if verdict["flagged"] else 0


def cmd_layout(dirs, args) -> int:
    from mosaic_tpu.sql.layout import advise_layout
    adv = advise_layout(store_root=args.store or None,
                        history_dir=dirs[0] if dirs else None)
    if args.json:
        json.dump({"grid_res": adv.grid_res,
                   "shard_rows": adv.shard_rows,
                   "reason": adv.reason,
                   "evidence": adv.evidence},
                  sys.stdout, indent=2, default=str)
        print()
        return 0
    print(f"recommended mosaic.store.grid.res   = {adv.grid_res}")
    print(f"recommended mosaic.store.shard.rows = {adv.shard_rows}")
    print(f"why: {adv.reason}")
    for src, ev in adv.evidence.items():
        print(f"  {src}: {ev}")
    if args.store:
        print(f"rewrite: mosaic_tpu.sql.layout.rewrite_store("
              f"{args.store!r}, <dst>) re-buckets and proves "
              f"read-back bit-parity")
    return 0


def cmd_report(dirs, args) -> int:
    rep = _merged(dirs, args.window_ms)
    json.dump(rep, sys.stdout, indent=2, default=str)
    print()
    return 0 if rep["totals"]["queries"] else 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    # --dir/--window-ms parse on BOTH sides of the subcommand: the
    # top-level parser owns real defaults, the subparsers share a
    # parent whose defaults are SUPPRESS so an after-subcommand
    # occurrence appends to (never clobbers) a before-subcommand one.
    # The parent must stay separate from the top-level options —
    # parents= shares action OBJECTS, and set_defaults on a shared
    # action would overwrite SUPPRESS for the subparsers too.
    _dir_help = ("history directory (repeatable for a fleet-wide "
                 "merge; default: MOSAIC_TPU_HISTORY_DIR / "
                 "configured mosaic.history.dir)")
    _win_help = ("re-window raw records at this width (default: "
                 "configured mosaic.history.window.ms)")
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--dir", action="append", dest="dir_after",
                        default=argparse.SUPPRESS, help=_dir_help)
    common.add_argument("--window-ms", type=float,
                        dest="window_ms_after",
                        default=argparse.SUPPRESS, help=_win_help)
    ap = argparse.ArgumentParser(
        prog="mosaicstat", description=__doc__.splitlines()[0])
    ap.add_argument("--dir", action="append", default=None,
                    help=_dir_help)
    ap.add_argument("--window-ms", type=float, default=None,
                    help=_win_help)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("top", parents=[common],
                       help="costliest queries (raw records)")
    p.add_argument("--by", default="wall_ms",
                   choices=["wall_ms", "device_s", "rows_in",
                            "rows_out", "h2d_bytes", "d2h_bytes",
                            "mem_peak_bytes", "compiles"])
    p.add_argument("--top", type=int, default=10)
    sub.add_parser("principals", parents=[common],
                   help="per-principal totals")
    sub.add_parser("strategies", parents=[common],
                   help="planner strategy win rates")
    p = sub.add_parser("heatmap", parents=[common],
                       help="partition heat ranking")
    p.add_argument("--top", type=int, default=20)
    p = sub.add_parser("diff", parents=[common],
                       help="window-over-window regression check")
    p.add_argument("--json", action="store_true",
                   help="machine-readable verdict")
    p = sub.add_parser("layout", parents=[common],
                       help="learned store-layout recommendation")
    p.add_argument("--store", default=None,
                   help="existing store root whose manifest seeds "
                        "the evidence (else heat/history only)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable recommendation")
    sub.add_parser("report", parents=[common],
                   help="full merged JSON report")
    args = ap.parse_args(argv)
    # fold after-subcommand occurrences into the top-level dests
    args.dir = ((args.dir or [])
                + list(getattr(args, "dir_after", None) or [])) or None
    if getattr(args, "window_ms_after", None) is not None:
        args.window_ms = args.window_ms_after

    dirs = _resolve_dirs(args)
    if not dirs and args.cmd != "layout":
        # layout can run from a store manifest (or heat) alone
        print("mosaicstat: no history dir (--dir, "
              "MOSAIC_TPU_HISTORY_DIR, or SET mosaic.history.dir)",
              file=sys.stderr)
        return 2
    handler = {"top": cmd_top, "principals": cmd_principals,
               "strategies": cmd_strategies, "heatmap": cmd_heatmap,
               "diff": cmd_diff, "layout": cmd_layout,
               "report": cmd_report}[args.cmd]
    rc = handler(dirs, args)
    if rc == 1 and args.cmd != "diff":   # diff prints its own reason
        print(f"mosaicstat: no records under "
              f"{', '.join(dirs)}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
