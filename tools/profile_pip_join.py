"""Profile the streamed PIP join with the continuous-profiling plane.

Thin CLI over ``mosaic_tpu.obs.profiler``: runs the flagship workload
through :func:`make_streamed_pip_join` with the host sampler running
and the kernel ledger collecting per-launch wall times, then prints
the report and (optionally) writes collapsed-stack /
speedscope-JSON / ``jax.profiler`` artifacts.  All measurement logic
lives in the library — this file only parses flags and formats output.

    python tools/profile_pip_join.py --n 1000000 --chunk 32768 \
        --hz 200 --speedscope /tmp/join.speedscope.json

Replaces the old hand-rolled per-stage timeit script; stage-level
decomposition now comes for free from the flamegraph (host frames) and
the ledger (device launches).
"""
import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=1 << 20,
                    help="points per batch (default 1M)")
    ap.add_argument("--iters", type=int, default=3,
                    help="timed warm iterations (default 3)")
    ap.add_argument("--chunk", type=int, default=None,
                    help="stream chunk rows (default: conf)")
    ap.add_argument("--hz", type=float, default=None,
                    help="host sampling rate (default: profiler's 97)")
    ap.add_argument("--collapsed", metavar="PATH",
                    help="write collapsed-stack text here")
    ap.add_argument("--speedscope", metavar="PATH",
                    help="write speedscope JSON here")
    ap.add_argument("--device-trace", metavar="LOGDIR",
                    help="also record a jax.profiler trace of the "
                         "timed iterations into LOGDIR")
    args = ap.parse_args(argv)

    import numpy as np
    import jax
    from mosaic_tpu.bench.workloads import build_workload, nyc_points
    from mosaic_tpu.obs import device_trace, start_profiler, \
        stop_profiler
    from mosaic_tpu.obs.profiler import ledger
    from mosaic_tpu.parallel.pip_join import (build_pip_index,
                                              make_streamed_pip_join)

    log("platform:", jax.devices()[0].platform)
    t0 = time.time()
    polys, grid, res = build_workload(n_side=16, grid_name="H3",
                                      zones="taxi")
    idx = build_pip_index(polys, res, grid)
    log(f"index build {time.time() - t0:.1f}s "
        f"({type(idx).__name__}, {len(polys)} zones)")

    run = make_streamed_pip_join(idx, grid, polys=polys,
                                 chunk=args.chunk)
    pts = nyc_points(args.n)
    run(pts)                        # warm: compile the chunk kernel
    ledger.reset()                  # timed iterations only

    prof = start_profiler(args.hz)
    times = []
    try:
        import contextlib
        dt_ctx = device_trace(args.device_trace) \
            if args.device_trace else contextlib.nullcontext()
        with dt_ctx:
            for _ in range(args.iters):
                t0 = time.time()
                run(pts)
                times.append(time.time() - t0)
    finally:
        report = prof.report(max_stacks=50)
        collapsed = prof.collapsed()
        speedscope = prof.speedscope(name="pip_join streamed")
        stop_profiler()

    wall = float(np.median(times))
    attributed = ledger.seconds("pip/streamed")
    log(f"{args.n} pts x {args.iters}: median {wall * 1e3:.1f} ms "
        f"({args.n / wall / 1e6:.2f}M pts/s); ledger attribution "
        f"{attributed / max(sum(times), 1e-9):.3f}")
    if args.collapsed:
        with open(args.collapsed, "w") as f:
            f.write(collapsed + "\n")
        log("collapsed stacks ->", args.collapsed)
    if args.speedscope:
        with open(args.speedscope, "w") as f:
            json.dump(speedscope, f)
        log("speedscope profile ->", args.speedscope)
    if args.device_trace:
        log("device trace ->", args.device_trace)
    print(json.dumps({
        "n": args.n, "iters": args.iters,
        "median_s": round(wall, 4),
        "pts_per_s": round(args.n / wall),
        "host": {"hz": report["hz"], "samples": report["samples"],
                 "distinct_stacks": report["distinct_stacks"]},
        "top_stacks": [{"frames": s["frames"][-3:], "count": s["count"]}
                       for s in report["stacks"][:5]],
        "ledger": ledger.report(),
    }, indent=2))


if __name__ == "__main__":
    main()
