"""Component-level timing of the PIP join on the real device."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def timeit(fn, *args, iters=5):
    out = jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(iters):
        t0 = time.time()
        jax.block_until_ready(fn(*args))
        ts.append(time.time() - t0)
    return float(np.median(ts)), out


def main():
    from mosaic_tpu.bench.workloads import build_workload, nyc_points
    from mosaic_tpu.parallel.pip_join import (build_pip_index, localize,
                                              make_pip_join_fn, pip_assign,
                                              _chip_pip, zone_histogram)
    from mosaic_tpu.ops.lookup import lookup

    platform = jax.devices()[0].platform
    log("platform:", platform)
    t0 = time.time()
    polys, grid, res = build_workload(n_side=16, grid_name="H3",
                                      zones="taxi")
    # this tool profiles the SORTED path's stages (chip_a/core_cells/
    # pip_assign are sorted-only); the dense path is profiled by bench.py
    idx = build_pip_index(polys, res, grid, dense="never")
    log(f"index build {time.time()-t0:.1f}s; chip_a shape "
        f"{idx.chip_a.shape}, core {idx.core_cells.shape}, "
        f"border {idx.border_cells.shape}, max_dup {idx.max_dup}")
    edge_counts = np.asarray(idx.chip_mask).sum(1)
    log("edges/chip: mean %.1f p50 %d p90 %d p99 %d max %d" % (
        edge_counts.mean(), *np.percentile(edge_counts,
                                           [50, 90, 99, 100]).astype(int)))

    n = 1 << 22
    pts64 = nyc_points(n)
    pts = jnp.asarray(localize(idx, pts64))

    # 1. cell assignment alone
    def cells_fn(p):
        absolute = p + idx.origin.astype(p.dtype)
        return grid.point_to_cell_jax_margin(absolute, idx.res)
    f1 = jax.jit(cells_fn)
    t, (cells, margin) = timeit(f1, pts)
    log(f"cell assignment: {t*1e3:.1f} ms ({n/t/1e6:.1f}M pts/s)")

    # 2. lookups alone
    cells = jax.block_until_ready(cells)

    def lookups_fn(c):
        s1, f1_ = lookup(idx.core_cells, c)
        s2, f2_ = lookup(idx.border_cells, c)
        return s1, f1_, s2, f2_
    t, _ = timeit(jax.jit(lookups_fn), cells)
    log(f"two lookups: {t*1e3:.1f} ms")

    # 3. single-dup chip pip (gather + parity + d2)
    s0 = jnp.zeros(n, jnp.int32)

    def one_dup(p, s):
        return _chip_pip(p, idx, s)
    t, _ = timeit(jax.jit(one_dup), pts, s0)
    log(f"one _chip_pip dup (zero slots): {t*1e3:.1f} ms")

    # random slots (realistic scattered gather)
    sr = jnp.asarray(np.random.default_rng(0).integers(
        0, idx.num_chips, n, dtype=np.int32))
    t, _ = timeit(jax.jit(one_dup), pts, sr)
    log(f"one _chip_pip dup (random slots): {t*1e3:.1f} ms")

    # 4. full pip_assign
    def assign_fn(p, c):
        return pip_assign(p, c, idx)
    t, _ = timeit(jax.jit(assign_fn), pts, cells)
    log(f"pip_assign (all {idx.max_dup} dups): {t*1e3:.1f} ms")

    # 5. full join
    join = make_pip_join_fn(idx, grid)
    t, _ = timeit(jax.jit(join), pts)
    log(f"full join: {t*1e3:.1f} ms ({n/t/1e6:.2f}M pts/s)")

    # 6. full join + histogram (bench step)
    def step(p):
        zone, unc = join(p)
        return zone, zone_histogram(zone, len(polys)), jnp.sum(unc)
    t, _ = timeit(jax.jit(step), pts)
    log(f"bench step: {t*1e3:.1f} ms ({n/t/1e6:.2f}M pts/s)")


if __name__ == "__main__":
    main()
