#!/bin/bash
# Probe the axon TPU backend every 10 minutes, appending one JSON line
# per attempt to tpu_probes_r05.jsonl. A down tunnel HANGS jax.devices()
# rather than erroring, so each probe is timeout-bounded. Provides the
# audit trail VERDICT.md (round 4, weak #8) asked for.
LOG=/root/repo/tpu_probes_r05.jsonl
while true; do
  TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
  OUT=$(timeout 120 python -c "import jax; d=jax.devices(); print(d[0].platform)" 2>/dev/null)
  RC=$?
  if [ "$RC" = "0" ] && [ -n "$OUT" ]; then
    echo "{\"ts\": \"$TS\", \"up\": true, \"platform\": \"$OUT\"}" >> "$LOG"
    # leave a flag file so the main loop notices quickly
    touch /root/repo/TPU_UP_FLAG
  else
    echo "{\"ts\": \"$TS\", \"up\": false, \"rc\": $RC}" >> "$LOG"
  fi
  sleep 600
done
