"""Validate the H3 projection rewrite and measure its error bounds.

Three checks:
1. host project_lattice (vector form) == geo_to_hex2d (polar form), f64.
2. device project_lattice_jax cells == host f64 cells wherever the margin
   exceeds the claimed error bound (both input paths).
3. empirical max planar-lattice error of the device paths vs host f64 —
   the numbers behind jaxkernel.ERR_LATTICE_DF / ERR_LATTICE_ABS.

Run with JAX_PLATFORMS=cpu for fast iteration and on the TPU to confirm
device numerics (division/transcendental lowering differs).
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    from mosaic_tpu.core.index.h3 import hexmath as hm
    from mosaic_tpu.core.index.h3 import index as ix
    from mosaic_tpu.core.index.h3.jaxkernel import (cell_from_lattice_jax,
                                                    project_lattice_jax)

    rng = np.random.default_rng(3)

    # ---- 1. host vector form vs polar form
    n = 200_000
    lat = np.arcsin(rng.uniform(-1, 1, n))
    lng = rng.uniform(-np.pi, np.pi, n)
    latlng = np.stack([lat, lng], axis=-1)
    for res in (0, 1, 7, 9, 15):
        f1, h1 = hm.geo_to_hex2d(latlng, res)
        f2, h2 = hm.project_lattice(latlng, res)
        assert np.array_equal(f1, f2)
        scale = hm.M_SQRT7 ** res
        rel = np.max(np.abs(h1 - h2)) / scale
        log(f"res {res}: host polar-vs-vector max diff {rel:.2e} "
            f"(lattice/scale units)")
        assert rel < 1e-9, rel

    # ---- 2+3. device paths vs host f64
    for res in (7, 9, 11):
        # city-scale window (the df path's regime)
        origin = np.array([-74.0, 40.7])
        m = 2_000_000
        loc = np.stack([rng.uniform(-0.4, 0.4, m),
                        rng.uniform(-0.3, 0.3, m)], axis=-1)
        abs_deg = loc + origin[None]
        latlng = np.radians(abs_deg[:, ::-1])
        fh, hex2d = hm.project_lattice(latlng, res)
        ijk = hm.hex2d_to_ijk(hex2d)
        ah = (ijk[:, 0] - ijk[:, 2]).astype(np.int64)
        bh = (ijk[:, 1] - ijk[:, 2]).astype(np.int64)

        from mosaic_tpu.core.index.h3.jaxkernel import err_lattice_bound

        def mk(prec, localized):
            if localized:
                return (jax.jit(lambda p: project_lattice_jax(
                    p, res, origin, precision=prec)),
                    jnp.asarray(loc, jnp.float32),
                    err_lattice_bound(res, prec, 0.4, localized=True))
            return (jax.jit(lambda p: project_lattice_jax(
                p, res, precision=prec)),
                jnp.asarray(abs_deg, jnp.float32),
                err_lattice_bound(res, prec, 75.0, localized=False))

        fns = {
            "df-local": mk("df", True),
            "df-abs": mk("df", False),
            "f64-local": mk("f64", True),
            "f64-abs": mk("f64", False),
        }
        for name, (fn, pts, bound) in fns.items():
            fd, ad, bd, margin, gap = [np.asarray(v) for v in fn(pts)]
            # planar error: host exact planar pos vs device lattice pick
            # (device residual vector reconstructs its planar estimate)
            same = (fd == fh) & (ad == ah) & (bd == bh)
            # max planar deviation: |device planar - host planar| via the
            # disagreement margin: for agreeing points, device planar =
            # lattice + residual; host planar known exactly.
            dev_planar_q = ad - bd + 0.0
            dev_planar_r = bd + 0.0
            # host axial float coords
            qf = hex2d[:, 0] - 0.5 * (hex2d[:, 1] / hm.M_SIN60)
            rf = hex2d[:, 1] / hm.M_SIN60
            # device float estimate = its lattice point + residual is not
            # returned; bound error instead by margin consistency:
            host_q = qf
            host_r = rf
            # error proxy: for disagreeing cells, host margin must be tiny
            disq = ~same
            host_fq = host_q - np.round(host_q)
            host_fr = host_r - np.round(host_r)
            vx = host_fq + 0.5 * host_fr
            vy = hm.M_SIN60 * host_fr
            proj = np.maximum(np.abs(vx), np.maximum(
                np.abs(0.5 * vx + hm.M_SIN60 * vy),
                np.abs(0.5 * vx - hm.M_SIN60 * vy)))
            host_margin = np.maximum(0.5 - proj, 0)
            worst = np.max(host_margin[disq]) if disq.any() else 0.0
            worst_dev = np.max(margin[disq]) if disq.any() else 0.0
            ok = "OK" if max(worst, worst_dev) < bound else "FAIL"
            log(f"res {res} path {name}: {disq.sum()}/{m} cell "
                f"disagreements, worst host-margin {worst:.3e} / "
                f"worst device-margin {worst_dev:.3e} vs bound "
                f"{bound:.3e} -> {ok}")
            # df bounds only hold where the compiler preserves Dekker
            # transforms (TPU); XLA:CPU collapses df to ~f32 (see
            # jaxkernel.pick_precision), so only f64 is asserted there.
            if name.startswith("f64") or jax.default_backend() != "cpu":
                assert max(worst, worst_dev) < bound, (name, res)
            # and full cell-id parity through aggregation where safe
            cd = np.asarray(jax.jit(cell_from_lattice_jax,
                                    static_argnums=(3,))(
                jnp.asarray(fd), jnp.asarray(ad), jnp.asarray(bd), res))
            ch = ix.latlng_to_cell(latlng[:200_000], res)
            eq = cd[:200_000] == ch
            bad = ~eq & same[:200_000]
            log(f"   id parity on agreeing lattice: "
                f"{bad.sum()} mismatches of 200k")
            assert bad.sum() == 0


if __name__ == "__main__":
    main()
